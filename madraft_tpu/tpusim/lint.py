"""simlint (ISSUE 15): jaxpr-level static analysis over every cached program.

The framework's core guarantees — lane independence, PRNG discipline,
exact-or-wide packing, zero-cost-when-off metrics/coverage — are enforced
dynamically by golden guards and bit-identity tests that re-EXECUTE programs.
This module proves the same structural invariants statically, by tracing each
cached program to its closed jaxpr (``jax.make_jaxpr`` on abstract inputs —
never executing anything) and running four lint passes over the equations:

``lane_isolation``
    Dependency analysis over the lane/cluster batch axis: every value whose
    axes carry lane identity is tracked through every primitive, and any op
    that MIXES lanes (reduce/cumsum/sort/partial-slice/concat/gather/scatter/
    dot along a lane-tagged axis) is a finding — the race-detector analogue
    for a vectorized simulator. The pool's declared harvest reductions (the
    monotone-id cumsum + retired count) and the coverage seen-set scatter are
    per-program ``allow`` rules, so the exceptions are enumerated, not silent.

``prng_discipline``
    Every ``random_bits`` draw is value-numbered back through its
    fold/split/seed chain. Two distinct draw sites reaching the SAME key are
    a finding (key reuse), a draw whose chain never roots in a program input
    is a finding (constant key), and a draw inside a loop whose key does not
    depend on that loop's carry is a finding (the same bits every iteration).
    Draw-parity groups additionally pin that metrics/coverage flags add ZERO
    draw sites statically — not just trajectory-pinned.

``packed_width``
    The hot-loop carry (fori/scan) of every packed program is suffix-aligned
    against the layout-derived expected carry (``jax.eval_shape`` of
    init+pack): a same-shape-but-wider leaf is a re-widening regression the
    ``bytes_per_lane`` bench gates only caught after the fact.

``zero_when_off``
    Metrics-off programs must carry zero-size metric leaves (same alignment,
    zero-dim expectation), no host callbacks may appear on any hot path, and
    non-coverage programs must contain no bitmap-sized values.

Trace-only by construction: the registry builds programs through the SAME
lru-cached factories the CLI uses, but only ever calls ``jax.make_jaxpr`` on
``ShapeDtypeStruct`` inputs — no compile, no execution, no HLO change.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import core

from .config import CoverageConfig, SimConfig, storm_profiles

# --------------------------------------------------------------- pass names
LANE_ISOLATION = "lane_isolation"
PRNG_DISCIPLINE = "prng_discipline"
PACKED_WIDTH = "packed_width"
ZERO_WHEN_OFF = "zero_when_off"
PASSES = (LANE_ISOLATION, PRNG_DISCIPLINE, PACKED_WIDTH, ZERO_WHEN_OFF)

# rule -> pass. Every finding carries one of these rule names; the pass is
# derived, so the report can group by either.
RULE_PASS = {
    "lane_reduce": LANE_ISOLATION,
    "lane_cumsum": LANE_ISOLATION,
    "lane_sort": LANE_ISOLATION,
    "lane_slice": LANE_ISOLATION,
    "lane_dus": LANE_ISOLATION,
    "lane_concat": LANE_ISOLATION,
    "lane_rev": LANE_ISOLATION,
    "lane_pad": LANE_ISOLATION,
    "lane_gather": LANE_ISOLATION,
    "lane_scatter": LANE_ISOLATION,
    "lane_contract": LANE_ISOLATION,
    "lane_branch": LANE_ISOLATION,
    "key_reuse": PRNG_DISCIPLINE,
    "constant_key": PRNG_DISCIPLINE,
    "loop_invariant_draw": PRNG_DISCIPLINE,
    "draw_parity": PRNG_DISCIPLINE,
    "wide_carry": PACKED_WIDTH,
    "narrow_carry": PACKED_WIDTH,
    "carry_shape_drift": PACKED_WIDTH,
    "carry_missing": PACKED_WIDTH,
    "metrics_leak": ZERO_WHEN_OFF,
    "host_callback": ZERO_WHEN_OFF,
    "coverage_leak": ZERO_WHEN_OFF,
}

# host-callback / device-to-host primitives that must never appear on a hot
# path (the static proxy for "no unexpected syncs": everything else in a
# jaxpr stays on device by construction)
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "infeed", "outfeed", "host_local_array_to_global_array",
})

# coverage seen-set sizes (default CLI bitmap + the ground-truth bitmap): a
# non-coverage program carrying a value with one of these dims has had the
# bitmap threaded into it
_COVERAGE_DIMS = frozenset({1 << 16, 1 << 14})


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding: ``rule`` names the defect, ``lint_pass`` the pass
    it belongs to (RULE_PASS), ``detail`` the op/leaf evidence."""

    program: str
    lint_pass: str
    rule: str
    detail: str

    def as_dict(self):
        return {"program": self.program, "pass": self.lint_pass,
                "rule": self.rule, "detail": self.detail}


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One registry entry: how to build a cached program on ABSTRACT inputs
    plus the static facts the passes check it against.

    ``build`` returns ``(jitted_fn, args)`` where args mix ShapeDtypeStruct
    leaves (traced) and concrete knob pytrees — exactly what the CLI would
    pass, minus any device data. ``n_lanes`` is the lane-axis size (0 for
    single-cluster programs: the lane pass is vacuous). ``expected_carry``
    returns the layout-derived carry pytree (ShapeDtypeStructs);
    ``carry_site`` says where to find the actual carry: the hot loop
    ("loop") or the program outputs' leading leaves ("out_prefix", for the
    loop-less init/harvest/unpack programs). ``allow`` enumerates the lane
    rules this program is DECLARED to hit (harvest reductions, coverage
    scatter) — hits are counted, not findings. ``draw_group`` names a
    draw-parity group: all members must have the same static draw-site
    count. ``golden_leg`` ties the entry to a golden-guard leg in
    golden_fuzz.json ("clean"/"bug"/"pool") — tests/test_trace.py
    enumerates the legs through the registry. ``needs_devices`` skips the
    entry (recorded, not silent) when fewer devices are attached."""

    name: str
    family: str
    build: Callable[[], tuple]
    n_lanes: int = 0
    metrics_off: bool = True
    coverage: bool = False
    expected_carry: Optional[Callable[[], Any]] = None
    carry_site: str = "loop"
    allow: frozenset = frozenset()
    draw_group: Optional[str] = None
    golden_leg: Optional[str] = None
    needs_devices: int = 1


# =========================================================================
# the jaxpr interpreter: lane tags + loop-variance + PRNG value numbers
# =========================================================================

class _Info:
    """Per-value abstract state: ``tags`` = set of axes carrying lane
    identity, ``loops`` = set of loop uids the value varies across,
    ``vn`` = PRNG-relevant value number (hashable)."""

    __slots__ = ("tags", "loops", "vn")

    def __init__(self, tags=frozenset(), loops=frozenset(), vn=("opaque",)):
        self.tags = tags
        self.loops = loops
        self.vn = vn


def _lit_key(val):
    a = np.asarray(val)
    if a.size <= 4:
        return ("lit", str(a.dtype), a.shape, tuple(a.reshape(-1).tolist()))
    return ("lit_big", str(a.dtype), a.shape)


def _params_sig(params):
    """Hashable signature of the simple (non-jaxpr) params, so value
    numbers distinguish e.g. two slices with different starts."""
    out = []
    for k in sorted(params):
        v = params[k]
        if isinstance(v, (int, float, bool, str, bytes, type(None))):
            out.append((k, v))
        elif isinstance(v, tuple) and all(
                isinstance(x, (int, float, bool, str)) for x in v):
            out.append((k, v))
        else:
            try:
                out.append((k, repr(v)))
            except Exception:
                pass
    return tuple(out)


_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin",
})
_CUM_PRIMS = frozenset({
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
})


def _reshape_groups(s_in, s_out):
    """Map in-axes to out-axes across a reshape, grouping contiguous axes
    whose products align. Returns a list of (in_axes, out_axes) groups."""
    groups, i, j = [], 0, 0
    n_in, n_out = len(s_in), len(s_out)
    while i < n_in or j < n_out:
        gi, gj = [i] if i < n_in else [], [j] if j < n_out else []
        pi = s_in[i] if i < n_in else 1
        pj = s_out[j] if j < n_out else 1
        i, j = i + (1 if i < n_in else 0), j + (1 if j < n_out else 0)
        while pi != pj:
            if pi < pj and i < n_in:
                pi *= s_in[i]
                gi.append(i)
                i += 1
            elif pj < pi and j < n_out:
                pj *= s_out[j]
                gj.append(j)
                j += 1
            else:
                return None  # ragged; caller falls back
        groups.append((gi, gj))
    return groups


class _Interp:
    """One walk of a program's closed jaxpr, collecting findings, draw
    sites, and the equation census. Loop bodies run to a tag fixpoint with
    collection off, then once more with collection on — so per-iteration
    equations are counted once and findings never duplicate."""

    def __init__(self, spec: ProgramSpec):
        self.spec = spec
        self.findings: dict = {}      # (rule, detail) -> None (ordered set)
        self.allowed: dict = {}       # rule -> hit count
        self.draws: list = []         # (key_vn, path, encl, key_loops, where)
        self.unknown: set = set()
        self.n_eqns = 0
        self._uid = itertools.count()

    # ---------------------------------------------------------------- util
    def flag(self, collect, rule, detail):
        if not collect:
            return
        if rule in self.spec.allow:
            self.allowed[rule] = self.allowed.get(rule, 0) + 1
        else:
            self.findings[(rule, detail)] = None

    def read(self, env, v):
        if isinstance(v, core.Literal):
            return _Info(vn=_lit_key(v.val))
        info = env.get(v)
        if info is None:
            info = _Info(vn=("free", next(self._uid)))
            env[v] = info
        return info

    def _const_infos(self, closed):
        return [_Info(vn=("const", _lit_key(c) if np.asarray(c).size <= 4
                          else ("big", i)))
                for i, c in enumerate(closed.consts)]

    # -------------------------------------------------------------- top
    def run_top(self, closed):
        n = self.spec.n_lanes
        args = []
        for i, v in enumerate(closed.jaxpr.invars):
            shape = tuple(getattr(v.aval, "shape", ()))
            tags = frozenset(a for a, d in enumerate(shape) if n and d == n)
            args.append(_Info(tags=tags, vn=("in", i)))
        self.run_jaxpr(closed.jaxpr, self._const_infos(closed), args,
                       True, (), frozenset())
        self._check_draws()

    def run_jaxpr(self, jaxpr, const_infos, arg_infos, collect, path, encl):
        env = {}
        for v, info in zip(jaxpr.constvars, const_infos):
            env[v] = info
        for v, info in zip(jaxpr.invars, arg_infos):
            env[v] = info
        for eqn in jaxpr.eqns:
            if collect:
                self.n_eqns += 1
            outs = self.eqn(eqn, env, collect, path, encl)
            for v, o in zip(eqn.outvars, outs):
                env[v] = o
        return [self.read(env, v) for v in jaxpr.outvars]

    # ------------------------------------------------------- default rule
    def _default(self, eqn, ins):
        """Numpy-style elementwise/broadcast: align trailing dims, union
        tags on equal-size dims (a size-1 broadcast dim is never the lane
        axis), union loop-variance, structural value number."""
        loops = frozenset().union(*(i.loops for i in ins)) if ins \
            else frozenset()
        vn = ("eq", eqn.primitive.name, _params_sig(eqn.params),
              tuple(i.vn for i in ins))
        outs = []
        for ov in eqn.outvars:
            oshape = tuple(getattr(ov.aval, "shape", ()))
            tags = set()
            for iv, info in zip(eqn.invars, ins):
                ishape = tuple(getattr(iv.aval, "shape", ()))
                off = len(oshape) - len(ishape)
                for a in info.tags:
                    if 0 <= a + off < len(oshape) and a < len(ishape) \
                            and ishape[a] == oshape[a + off]:
                        tags.add(a + off)
            outs.append(_Info(frozenset(tags), loops, vn))
        return outs

    def _sized_fallback(self, eqn, ins):
        """Unknown primitive: keep soundness by size — any output axis whose
        size equals the lane count is tagged when ANY input was tagged."""
        self.unknown.add(eqn.primitive.name)
        tagged = any(i.tags for i in ins)
        loops = frozenset().union(*(i.loops for i in ins)) if ins \
            else frozenset()
        n = self.spec.n_lanes
        outs = []
        for ov in eqn.outvars:
            shape = tuple(getattr(ov.aval, "shape", ()))
            tags = frozenset(a for a, d in enumerate(shape)
                             if tagged and n and d == n)
            outs.append(_Info(tags, loops, ("opq", next(self._uid))))
        return outs

    # ------------------------------------------------------------ the eqn
    def eqn(self, eqn, env, collect, path, encl):
        prim = eqn.primitive.name
        ins = [self.read(env, v) for v in eqn.invars]
        # flat handlers (no sub-jaxpr) need the walk position for draw
        # bookkeeping; stash it rather than widening every signature
        self._path, self._encl = path, encl

        if prim == "pjit":
            cj = eqn.params["jaxpr"]
            return self.run_jaxpr(cj.jaxpr, self._const_infos(cj), ins,
                                  collect, path, encl)
        if prim == "while":
            return self._while(eqn, ins, collect, path, encl)
        if prim == "scan":
            return self._scan(eqn, ins, collect, path, encl)
        if prim == "cond":
            return self._cond(eqn, ins, collect, path, encl)

        if prim in _CALLBACK_PRIMS:
            self.flag(collect, "host_callback",
                      f"{prim} reaches the compiled program")
            return [_Info() for _ in eqn.outvars]

        handler = getattr(self, "_p_" + prim.replace("-", "_"), None)
        if handler is not None:
            return handler(eqn, ins, collect)

        if prim in _REDUCE_PRIMS:
            return self._reduce(eqn, ins, collect)
        if prim in _CUM_PRIMS:
            return self._cum(eqn, ins, collect)
        if prim.startswith("scatter"):
            return self._scatter(eqn, ins, collect)

        # generic call-like primitive carrying a sub-jaxpr with matching
        # arity (custom_jvp/vjp, remat, closed_call, ...)
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            cj = eqn.params.get(key)
            if isinstance(cj, core.ClosedJaxpr) \
                    and len(cj.jaxpr.invars) == len(eqn.invars):
                return self.run_jaxpr(cj.jaxpr, self._const_infos(cj), ins,
                                      collect, path, encl)
            if isinstance(cj, core.Jaxpr) \
                    and len(cj.invars) == len(eqn.invars):
                return self.run_jaxpr(
                    cj, [_Info() for _ in cj.constvars], ins,
                    collect, path, encl)

        known_default = prim in {
            "add", "sub", "mul", "div", "rem", "max", "min", "pow", "and",
            "or", "xor", "not", "neg", "abs", "sign", "floor", "ceil",
            "round", "exp", "log", "tanh", "logistic", "sqrt", "rsqrt",
            "integer_pow", "shift_left", "shift_right_logical",
            "shift_right_arithmetic", "eq", "ne", "lt", "le", "gt", "ge",
            "select_n", "convert_element_type", "clamp", "erf", "erf_inv",
            "is_finite", "stop_gradient", "copy", "sharding_constraint",
            "device_put", "nextafter", "population_count", "clz",
            "reduce_precision", "real", "imag", "square", "sin", "cos",
        }
        if known_default:
            return self._default(eqn, ins)
        if all(tuple(getattr(ov.aval, "shape", ())) in
               [tuple(getattr(iv.aval, "shape", ())) for iv in eqn.invars]
               for ov in eqn.outvars):
            # unknown but shape-preserving: elementwise treatment is sound
            self.unknown.add(prim)
            return self._default(eqn, ins)
        return self._sized_fallback(eqn, ins)

    # -------------------------------------------------- structured prims
    def _while(self, eqn, ins, collect, path, encl):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cj, bj = p["cond_jaxpr"], p["body_jaxpr"]
        cconsts, bconsts, init = ins[:cn], ins[cn:cn + bn], ins[cn + bn:]
        uid = next(self._uid)
        sub_encl = encl | {uid}
        carry = [_Info(i.tags, i.loops | {uid}, ("carry", uid, k))
                 for k, i in enumerate(init)]
        for _ in range(32):  # tag fixpoint (bounded by total rank)
            outs = self.run_jaxpr(bj.jaxpr, self._const_infos(bj),
                                  bconsts + carry, False, path, sub_encl)
            new = [_Info(c.tags | o.tags, c.loops, c.vn)
                   for c, o in zip(carry, outs)]
            stable = all(n.tags == c.tags for n, c in zip(new, carry))
            carry = new
            if stable:
                break
        self.run_jaxpr(cj.jaxpr, self._const_infos(cj), cconsts + carry,
                       collect, path, sub_encl)
        self.run_jaxpr(bj.jaxpr, self._const_infos(bj), bconsts + carry,
                       collect, path, sub_encl)
        out_loops = frozenset().union(*(i.loops for i in init)) if init \
            else frozenset()
        return [_Info(c.tags, out_loops, ("loopout", uid, k))
                for k, c in enumerate(carry)]

    def _scan(self, eqn, ins, collect, path, encl):
        p = eqn.params
        nc, ncar = p["num_consts"], p["num_carry"]
        cj = p["jaxpr"]
        consts_i, init, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]
        uid = next(self._uid)
        sub_encl = encl | {uid}
        xslices = [_Info(frozenset(a - 1 for a in i.tags if a > 0),
                         i.loops | {uid}, ("xs", uid, k))
                   for k, i in enumerate(xs)]
        carry = [_Info(i.tags, i.loops | {uid}, ("carry", uid, k))
                 for k, i in enumerate(init)]
        n_body_out = len(cj.jaxpr.outvars)
        for _ in range(32):
            outs = self.run_jaxpr(cj.jaxpr, self._const_infos(cj),
                                  consts_i + carry + xslices, False, path,
                                  sub_encl)
            new = [_Info(c.tags | o.tags, c.loops, c.vn)
                   for c, o in zip(carry, outs[:ncar])]
            stable = all(n.tags == c.tags for n, c in zip(new, carry))
            carry = new
            if stable:
                break
        outs = self.run_jaxpr(cj.jaxpr, self._const_infos(cj),
                              consts_i + carry + xslices, collect, path,
                              sub_encl)
        out_loops = frozenset().union(*(i.loops for i in ins)) if ins \
            else frozenset()
        res = [_Info(c.tags, out_loops, ("loopout", uid, k))
               for k, c in enumerate(carry)]
        for k, y in enumerate(outs[ncar:n_body_out]):
            res.append(_Info(frozenset(a + 1 for a in y.tags), out_loops,
                             ("ys", uid, k)))
        return res

    def _cond(self, eqn, ins, collect, path, encl):
        branches = eqn.params["branches"]
        pred, ops = ins[0], ins[1:]
        if pred.tags:
            self.flag(collect, "lane_branch",
                      "cond predicate carries the lane axis")
        uid = next(self._uid)
        per_branch = []
        for bi, br in enumerate(branches):
            outs = self.run_jaxpr(br.jaxpr, self._const_infos(br), ops,
                                  collect, path + ((uid, bi),), encl)
            per_branch.append(outs)
        res = []
        for k in range(len(eqn.outvars)):
            tags = frozenset().union(*(b[k].tags for b in per_branch))
            loops = frozenset().union(*(b[k].loops for b in per_branch))
            vns = {b[k].vn for b in per_branch}
            vn = vns.pop() if len(vns) == 1 else ("cond", uid, k)
            res.append(_Info(tags, loops, vn))
        return res

    # ------------------------------------------------------- shape prims
    def _p_broadcast_in_dim(self, eqn, ins, collect):
        bd = eqn.params["broadcast_dimensions"]
        ishape = tuple(eqn.invars[0].aval.shape)
        oshape = tuple(eqn.outvars[0].aval.shape)
        tags = frozenset(bd[a] for a in ins[0].tags
                         if ishape[a] == oshape[bd[a]])
        return [_Info(tags, ins[0].loops,
                      ("eq", "broadcast", _params_sig(eqn.params),
                       (ins[0].vn,)))]

    def _p_transpose(self, eqn, ins, collect):
        perm = eqn.params["permutation"]
        tags = frozenset(j for j, src in enumerate(perm)
                         if src in ins[0].tags)
        return [_Info(tags, ins[0].loops,
                      ("eq", "transpose", tuple(perm), (ins[0].vn,)))]

    def _p_squeeze(self, eqn, ins, collect):
        dims = set(eqn.params["dimensions"])
        ishape = tuple(eqn.invars[0].aval.shape)
        remap, j = {}, 0
        for a in range(len(ishape)):
            if a in dims:
                continue
            remap[a] = j
            j += 1
        tags = frozenset(remap[a] for a in ins[0].tags if a in remap)
        return [_Info(tags, ins[0].loops,
                      ("eq", "squeeze", tuple(sorted(dims)), (ins[0].vn,)))]

    def _p_expand_dims(self, eqn, ins, collect):
        dims = set(eqn.params["dimensions"])
        orank = len(eqn.outvars[0].aval.shape)
        remap, j = {}, 0
        for a in range(orank):
            if a in dims:
                continue
            remap[j] = a
            j += 1
        tags = frozenset(remap[a] for a in ins[0].tags if a in remap)
        return [_Info(tags, ins[0].loops,
                      ("eq", "expand", tuple(sorted(dims)), (ins[0].vn,)))]

    def _p_reshape(self, eqn, ins, collect):
        ishape = tuple(eqn.invars[0].aval.shape)
        oshape = tuple(eqn.outvars[0].aval.shape)
        groups = _reshape_groups(ishape, oshape)
        tags = set()
        if groups is None:
            if ins[0].tags:
                n = self.spec.n_lanes
                tags = {a for a, d in enumerate(oshape) if n and d == n}
        else:
            for gi, gj in groups:
                if any(a in ins[0].tags for a in gi):
                    tags.update(gj)
        return [_Info(frozenset(tags), ins[0].loops,
                      ("eq", "reshape", (ishape, oshape), (ins[0].vn,)))]

    def _p_iota(self, eqn, ins, collect):
        dim = eqn.params["dimension"]
        shape = tuple(eqn.outvars[0].aval.shape)
        n = self.spec.n_lanes
        tags = frozenset({dim} if n and shape[dim] == n else set())
        return [_Info(tags, frozenset(),
                      ("eq", "iota", _params_sig(eqn.params), ()))]

    # --------------------------------------------------- lane-mixing prims
    def _axes_detail(self, eqn, axes):
        shapes = [tuple(getattr(v.aval, "shape", ())) for v in eqn.invars]
        return (f"{eqn.primitive.name} over lane axis "
                f"{sorted(axes)} of {shapes[0] if shapes else ()}")

    def _reduce(self, eqn, ins, collect):
        axes = set(eqn.params.get("axes", ()))
        hit = axes & set(ins[0].tags)
        if hit:
            self.flag(collect, "lane_reduce", self._axes_detail(eqn, hit))
        ishape = tuple(eqn.invars[0].aval.shape)
        remap, j = {}, 0
        for a in range(len(ishape)):
            if a in axes:
                continue
            remap[a] = j
            j += 1
        tags = frozenset(remap[a] for a in ins[0].tags if a in remap)
        loops = frozenset().union(*(i.loops for i in ins))
        vn = ("eq", eqn.primitive.name, tuple(sorted(axes)),
              tuple(i.vn for i in ins))
        return [_Info(tags, loops, vn) for _ in eqn.outvars]

    def _cum(self, eqn, ins, collect):
        axis = eqn.params.get("axis", 0)
        if axis in ins[0].tags:
            self.flag(collect, "lane_cumsum", self._axes_detail(eqn, {axis}))
        return self._default(eqn, ins)

    def _p_sort(self, eqn, ins, collect):
        dim = eqn.params.get("dimension", -1)
        if any(dim in i.tags for i in ins):
            self.flag(collect, "lane_sort", self._axes_detail(eqn, {dim}))
        loops = frozenset().union(*(i.loops for i in ins))
        tags = frozenset().union(*(i.tags for i in ins))
        vn = ("eq", "sort", _params_sig(eqn.params),
              tuple(i.vn for i in ins))
        return [_Info(tags, loops, vn) for _ in eqn.outvars]

    def _p_slice(self, eqn, ins, collect):
        p = eqn.params
        ishape = tuple(eqn.invars[0].aval.shape)
        starts, limits = p["start_indices"], p["limit_indices"]
        strides = p.get("strides") or (1,) * len(ishape)
        for a in ins[0].tags:
            if starts[a] != 0 or limits[a] != ishape[a] or strides[a] != 1:
                self.flag(collect, "lane_slice",
                          f"partial slice [{starts[a]}:{limits[a]}] on lane "
                          f"axis {a} of {ishape}")
        return [_Info(ins[0].tags, ins[0].loops,
                      ("eq", "slice", _params_sig(p), (ins[0].vn,)))]

    def _p_dynamic_slice(self, eqn, ins, collect):
        sizes = eqn.params["slice_sizes"]
        ishape = tuple(eqn.invars[0].aval.shape)
        for a in ins[0].tags:
            if sizes[a] != ishape[a]:
                self.flag(collect, "lane_slice",
                          f"dynamic_slice size {sizes[a]} on lane axis {a} "
                          f"of {ishape}")
        loops = frozenset().union(*(i.loops for i in ins))
        return [_Info(ins[0].tags, loops,
                      ("eq", "dslice", _params_sig(eqn.params),
                       tuple(i.vn for i in ins)))]

    def _p_dynamic_update_slice(self, eqn, ins, collect):
        oshape = tuple(eqn.invars[0].aval.shape)
        ushape = tuple(eqn.invars[1].aval.shape)
        for a in ins[0].tags | ins[1].tags:
            if a < len(ushape) and ushape[a] != oshape[a]:
                self.flag(collect, "lane_dus",
                          f"partial dynamic_update_slice on lane axis {a}: "
                          f"update {ushape} into {oshape}")
        loops = frozenset().union(*(i.loops for i in ins))
        return [_Info(ins[0].tags | ins[1].tags, loops,
                      ("eq", "dus", (), tuple(i.vn for i in ins)))]

    def _p_rev(self, eqn, ins, collect):
        dims = set(eqn.params["dimensions"])
        hit = dims & set(ins[0].tags)
        if hit:
            self.flag(collect, "lane_rev", self._axes_detail(eqn, hit))
        return self._default(eqn, ins)

    def _p_pad(self, eqn, ins, collect):
        cfgs = eqn.params["padding_config"]
        for a in ins[0].tags:
            if cfgs[a] != (0, 0, 0):
                self.flag(collect, "lane_pad",
                          f"pad {cfgs[a]} on lane axis {a}")
        return [_Info(ins[0].tags, ins[0].loops | ins[1].loops,
                      ("eq", "pad", _params_sig(eqn.params),
                       (ins[0].vn, ins[1].vn)))]

    def _p_concatenate(self, eqn, ins, collect):
        dim = eqn.params["dimension"]
        if any(dim in i.tags for i in ins):
            self.flag(collect, "lane_concat",
                      f"concatenate along lane axis {dim}")
        tags = frozenset().union(*(i.tags for i in ins))
        loops = frozenset().union(*(i.loops for i in ins))
        return [_Info(tags, loops,
                      ("eq", "concat", (dim,), tuple(i.vn for i in ins)))]

    def _p_gather(self, eqn, ins, collect):
        dn = eqn.params["dimension_numbers"]
        sizes = eqn.params["slice_sizes"]
        op, idx = ins[0], ins[1]
        oshape = tuple(eqn.invars[0].aval.shape)
        ishape = tuple(eqn.invars[1].aval.shape)
        out_rank = len(eqn.outvars[0].aval.shape)
        ob = tuple(getattr(dn, "operand_batching_dims", ()) or ())
        sib = tuple(getattr(dn, "start_indices_batching_dims", ()) or ())
        offset_dims = tuple(dn.offset_dims)
        collapsed = set(dn.collapsed_slice_dims) | set(ob)
        # which operand dims are indexed into / partially sliced
        for a in op.tags:
            if a in ob:
                continue  # per-lane batched indexing: safe by construction
            if a in dn.start_index_map:
                self.flag(collect, "lane_gather",
                          f"gather indexes INTO lane axis {a} of {oshape}")
            elif sizes[a] != oshape[a]:
                self.flag(collect, "lane_gather",
                          f"gather takes partial slice {sizes[a]} of lane "
                          f"axis {a} ({oshape})")
        # output tag mapping
        try:
            batch_out = [a for a in range(out_rank) if a not in offset_dims]
            idx_dims = [a for a in range(len(ishape) - 1)]
            tags = set()
            for pos, a in enumerate(batch_out):
                if pos < len(idx_dims):
                    src = idx_dims[pos]
                    if src in idx.tags:
                        tags.add(a)
                    if src in sib and ob:
                        k = sib.index(src)
                        if k < len(ob) and ob[k] in op.tags:
                            tags.add(a)
            full = [a for a in range(len(oshape))
                    if a not in collapsed and a not in dn.start_index_map]
            for pos, a in enumerate(full):
                if pos < len(offset_dims) and a in op.tags \
                        and sizes[a] == oshape[a]:
                    tags.add(offset_dims[pos])
            tags = frozenset(tags)
        except Exception:
            n = self.spec.n_lanes
            tags = frozenset(a for a in range(out_rank)
                             if n and eqn.outvars[0].aval.shape[a] == n
                             and (op.tags or idx.tags))
        loops = op.loops | idx.loops
        return [_Info(tags, loops,
                      ("eq", "gather", _params_sig(eqn.params),
                       (op.vn, idx.vn)))]

    def _scatter(self, eqn, ins, collect):
        dn = eqn.params["dimension_numbers"]
        op, idx, upd = ins[0], ins[1], ins[2]
        oshape = tuple(eqn.invars[0].aval.shape)
        ob = set(getattr(dn, "operand_batching_dims", ()) or ())
        sdod = set(dn.scatter_dims_to_operand_dims)
        for a in op.tags:
            if a in ob:
                continue
            if a in sdod:
                self.flag(collect, "lane_scatter",
                          f"{eqn.primitive.name} writes data-dependent "
                          f"positions of lane axis {a} ({oshape})")
        sib = set(getattr(dn, "scatter_indices_batching_dims", ()) or ())
        idx_bad = any(a not in sib for a in idx.tags)
        if not op.tags and (idx_bad or (upd.tags and not ob)):
            # lanes writing into a SHARED operand (the coverage seen-set
            # pattern): cross-lane dataflow through the target. A vmapped
            # per-lane scatter is NOT this — there the lane axis rides the
            # batching dims and every lane writes its own row.
            self.flag(collect, "lane_scatter",
                      f"{eqn.primitive.name}: lane-tagged indices/updates "
                      f"write a shared operand {oshape}")
        loops = op.loops | idx.loops | upd.loops
        return [_Info(op.tags, loops,
                      ("eq", eqn.primitive.name, (),
                       (op.vn, idx.vn, upd.vn)))]

    def _p_dot_general(self, eqn, ins, collect):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = ins[0], ins[1]
        for a in lhs.tags:
            if a in lc:
                self.flag(collect, "lane_contract",
                          f"dot_general contracts lane axis {a} of lhs")
        for a in rhs.tags:
            if a in rc:
                self.flag(collect, "lane_contract",
                          f"dot_general contracts lane axis {a} of rhs")
        lshape = tuple(eqn.invars[0].aval.shape)
        rshape = tuple(eqn.invars[1].aval.shape)
        tags = set()
        for pos, (la, ra) in enumerate(zip(lb, rb)):
            if la in lhs.tags or ra in rhs.tags:
                tags.add(pos)
        lfree = [a for a in range(len(lshape)) if a not in lc and a not in lb]
        rfree = [a for a in range(len(rshape)) if a not in rc and a not in rb]
        for pos, a in enumerate(lfree):
            if a in lhs.tags:
                tags.add(len(lb) + pos)
        for pos, a in enumerate(rfree):
            if a in rhs.tags:
                tags.add(len(lb) + len(lfree) + pos)
        return [_Info(frozenset(tags), lhs.loops | rhs.loops,
                      ("eq", "dot", _params_sig(eqn.params),
                       (lhs.vn, rhs.vn)))]

    # ---------------------------------------------------------- PRNG prims
    def _p_random_seed(self, eqn, ins, collect):
        return [_Info(ins[0].tags, ins[0].loops, ("seed", ins[0].vn))]

    def _p_random_wrap(self, eqn, ins, collect):
        tags = frozenset(a for a in ins[0].tags
                         if a < len(eqn.invars[0].aval.shape) - 1)
        return [_Info(tags, ins[0].loops, ins[0].vn)]

    def _p_random_unwrap(self, eqn, ins, collect):
        return [_Info(ins[0].tags, ins[0].loops, ins[0].vn)]

    def _p_random_fold_in(self, eqn, ins, collect):
        key, data = ins[0], ins[1]
        kshape = tuple(eqn.invars[0].aval.shape)
        dshape = tuple(eqn.invars[1].aval.shape)
        oshape = tuple(eqn.outvars[0].aval.shape)
        tags = set()
        for info, shape in ((key, kshape), (data, dshape)):
            off = len(oshape) - len(shape)
            for a in info.tags:
                if 0 <= a + off < len(oshape) and shape[a] == oshape[a + off]:
                    tags.add(a + off)
        return [_Info(frozenset(tags), key.loops | data.loops,
                      ("fold", key.vn, data.vn))]

    def _p_random_split(self, eqn, ins, collect):
        return [_Info(ins[0].tags, ins[0].loops, ("split", ins[0].vn))]

    def _p_random_bits(self, eqn, ins, collect):
        key = ins[0]
        if collect:
            kshape = tuple(eqn.invars[0].aval.shape)
            oshape = tuple(eqn.outvars[0].aval.shape)
            self.draws.append((key.vn, self._path, self._encl, key.loops,
                               f"random_bits{oshape} from key{kshape}"))
        tags = frozenset(a for a in key.tags)  # key batch dims lead
        return [_Info(tags, key.loops, ("bits", key.vn))]

    # draw bookkeeping needs the walk position; stash it around the dispatch
    _path: tuple = ()
    _encl: frozenset = frozenset()

    # ------------------------------------------------------- PRNG findings
    def _check_draws(self):
        def vn_roots(vn, acc):
            if isinstance(vn, tuple):
                if vn and vn[0] in ("in", "carry", "xs"):
                    acc.add(vn[0])
                for x in vn:  # ALL elements: child-vn tuples start at [0]
                    if isinstance(x, tuple):
                        vn_roots(x, acc)
            return acc

        def exclusive(p1, p2):
            d1 = dict(p1)
            return any(d1.get(c, b) != b for c, b in p2)

        for i, (vn, path, encl, kloops, where) in enumerate(self.draws):
            roots = vn_roots(vn, set())
            if not roots & {"in", "carry", "xs"}:
                self.findings[("constant_key",
                               f"{where}: key chain never reaches a program "
                               f"input")] = None
            missing = encl - kloops
            if missing:
                self.findings[("loop_invariant_draw",
                               f"{where}: key is invariant across "
                               f"{len(missing)} enclosing loop(s) — same "
                               f"bits every iteration")] = None
            for j in range(i + 1, len(self.draws)):
                vn2, path2, _, _, where2 = self.draws[j]
                if vn == vn2 and not exclusive(path, path2):
                    self.findings[("key_reuse",
                                   f"{where} and {where2} consume the SAME "
                                   f"key chain")] = None


# =========================================================================
# pass 3/4: carry-layout alignment against the layout-derived expectation
# =========================================================================

def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                if isinstance(sub, core.ClosedJaxpr):
                    yield from _iter_eqns(sub.jaxpr)
                elif isinstance(sub, core.Jaxpr):
                    yield from _iter_eqns(sub)


def _loop_carries(jaxpr):
    """Every (kind, carry avals) loop in the program, recursively."""
    out = []
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name == "while":
            nc = eqn.params["cond_nconsts"] + eqn.params["body_nconsts"]
            out.append(("while", [v.aval for v in eqn.invars[nc:]]))
        elif eqn.primitive.name == "scan":
            nc, ncar = eqn.params["num_consts"], eqn.params["num_carry"]
            out.append(("scan", [v.aval for v in eqn.invars[nc:nc + ncar]]))
    return out


def _leaf_names(tree):
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(p) or f"[{i}]"
            for i, (p, _) in enumerate(paths)]


def _classify_leaf(spec, name, a, e):
    ashape, eshape = tuple(a.shape), tuple(e.shape)
    adt, edt = jnp.dtype(a.dtype), jnp.dtype(e.dtype)
    if ashape == eshape and adt == edt:
        return None
    if 0 in eshape and 0 not in ashape:
        return ("metrics_leak",
                f"carry leaf {name}: expected zero-size {eshape} under the "
                f"off flag, program carries {ashape} {adt}")
    if ashape == eshape:
        if adt.itemsize > edt.itemsize:
            return ("wide_carry",
                    f"carry leaf {name}: {adt} where the packed layout "
                    f"derives {edt} ({eshape}) — re-widening regression")
        return ("narrow_carry",
                f"carry leaf {name}: {adt} narrower than the layout's "
                f"{edt} ({eshape})")
    return ("carry_shape_drift",
            f"carry leaf {name}: {ashape} {adt} != layout {eshape} {edt}")


def _check_carry(spec, closed):
    if spec.expected_carry is None:
        return []
    exp_tree = spec.expected_carry()
    exp = jax.tree_util.tree_leaves(exp_tree)
    names = _leaf_names(exp_tree)
    if spec.carry_site == "out_prefix":
        actual = [v.aval for v in closed.jaxpr.outvars]
        if len(actual) < len(exp):
            return [("carry_missing",
                     f"program has {len(actual)} outputs, layout expects "
                     f">= {len(exp)} leading state leaves")]
        actual = actual[:len(exp)]
    else:
        best, best_score = None, -1
        for kind, carry in _loop_carries(closed.jaxpr):
            if len(carry) < len(exp):
                continue
            tail = carry[-len(exp):]
            score = sum(
                1 for a, e in zip(tail, exp)
                if tuple(a.shape) == tuple(e.shape)
                and jnp.dtype(a.dtype) == jnp.dtype(e.dtype))
            if score > best_score:
                best, best_score = tail, score
        if best is None:
            return [("carry_missing",
                     "no fori/scan loop with a carry at least as large as "
                     "the layout-derived state found")]
        actual = best
    out = []
    for name, a, e in zip(names, actual, exp):
        r = _classify_leaf(spec, name, a, e)
        if r is not None:
            out.append(r)
    return out


def _check_zero_off(spec, closed):
    out = []
    if spec.coverage:
        return out
    seen = set()
    for eqn in _iter_eqns(closed.jaxpr):
        for v in eqn.outvars:
            for d in getattr(v.aval, "shape", ()):
                if d in _COVERAGE_DIMS and d not in seen:
                    seen.add(d)
                    out.append((
                        "coverage_leak",
                        f"non-coverage program materializes a {d}-bit "
                        f"seen-set-sized value "
                        f"({tuple(v.aval.shape)} {v.aval.dtype})"))
    return out


# =========================================================================
# running the passes
# =========================================================================

def lint_program(spec: ProgramSpec):
    """Trace one registry entry and run all four passes. Returns
    ``(info_dict, [Finding])``; a skipped entry (too few devices) returns
    an info row with ``skipped`` set and no findings."""
    info = {"name": spec.name, "family": spec.family,
            "lanes": spec.n_lanes, "eqns": 0, "draws": 0,
            "skipped": None, "allowed": {}}
    ndev = len(jax.devices())
    if spec.needs_devices > ndev:
        info["skipped"] = (f"needs {spec.needs_devices} devices, "
                           f"have {ndev}")
        return info, [], None
    fn, args = spec.build()
    closed = jax.make_jaxpr(fn)(*args)
    interp = _Interp(spec)
    interp.run_top(closed)
    raw = list(interp.findings)
    raw += _check_carry(spec, closed)
    raw += _check_zero_off(spec, closed)
    findings = [Finding(spec.name, RULE_PASS.get(rule, LANE_ISOLATION),
                        rule, detail)
                for rule, detail in raw]
    info["eqns"] = interp.n_eqns
    info["draws"] = len(interp.draws)
    info["allowed"] = dict(interp.allowed)
    return info, findings, interp


def run_lint(specs, program: Optional[str] = None):
    """Run the lint passes over ``specs`` (optionally filtered by substring
    ``program``) and build the report dict (schema in MIGRATION.md)."""
    if program is not None:
        specs = [s for s in specs if program in s.name]
    infos, findings = [], []
    group_draws: dict = {}
    for spec in specs:
        info, f, _ = lint_program(spec)
        infos.append(info)
        findings.extend(f)
        if spec.draw_group and info["skipped"] is None:
            group_draws.setdefault(spec.draw_group, []).append(
                (spec.name, info["draws"]))
    for group, members in sorted(group_draws.items()):
        counts = {c for _, c in members}
        if len(counts) > 1:
            detail = ", ".join(f"{n}={c}" for n, c in members)
            for name, c in members:
                if c != min(counts):
                    findings.append(Finding(
                        name, PRNG_DISCIPLINE, "draw_parity",
                        f"draw-site count diverges within group "
                        f"{group!r}: {detail}"))
    per_pass = {p: 0 for p in PASSES}
    for f in findings:
        per_pass[f.lint_pass] += 1
    return {
        "schema": 1,
        "programs": infos,
        "findings": [f.as_dict() for f in findings],
        "summary": {
            "programs": len(infos),
            "traced": sum(1 for i in infos if i["skipped"] is None),
            "skipped": sum(1 for i in infos if i["skipped"] is not None),
            "findings": len(findings),
            "per_pass": per_pass,
        },
    }


# =========================================================================
# the program registry
# =========================================================================

_S = jax.ShapeDtypeStruct
_I32 = jnp.int32
_U32 = jnp.uint32


def _dims_of(tree):
    return {int(d) for leaf in jax.tree_util.tree_leaves(tree)
            for d in leaf.shape}


def _batched(tree, n):
    return jax.tree_util.tree_map(
        lambda l: _S((n,) + tuple(l.shape), l.dtype), tree)


def _pick_lanes(forbidden, even=True):
    """A lane-axis size that collides with NO other dim in the program's
    states/configs, so size-based top-level tagging cannot mis-tag an inner
    axis. Even (mesh- and 2-shard-divisible) candidates first."""
    cands = (6, 14, 22, 26, 34, 38, 46) if even else (7, 11, 13, 17, 19, 23)
    for c in cands:
        if c not in forbidden:
            return c
    return 58


def _cfg_dims(*cfgs):
    out = {1, 2, 3, 4, 5, 16, 10}
    for cfg in cfgs:
        for f in dataclasses.fields(cfg):
            v = getattr(cfg, f.name)
            if isinstance(v, int) and not isinstance(v, bool):
                out.add(v)
    return out


# The pinned cached-program count (ISSUE 17): the host-side telemetry
# plane (tpusim/telemetry.py) must add NO compiled programs and no host
# callbacks to any hot-path jaxpr — the zero_when_off discipline extended
# to the whole registry. Callback primitives are caught per-equation by
# _CALLBACK_PRIMS above for EVERY traced program; this count catches the
# other half (a new program sneaking in off-registry or on it silently).
# A deliberate new program updates this constant in the same commit that
# registers it.
REGISTRY_PROGRAMS = 31


def registry() -> list:
    """Every cached compiled program, with its static config and abstract
    input shapes — the single enumeration the lint passes, the golden
    guards (tests/test_trace.py), and future optimization-matrix knobs
    share. Lane counts are auto-picked per family to avoid colliding with
    any config/state dimension (ShardKvConfig alone has two dims of 6)."""
    from . import engine
    from .config import Knobs  # noqa: F401 (knobs ride in concrete)
    from .ctrler import (CtrlerConfig, _ctrler_program,
                         _ctrler_replay_program, init_ctrler_cluster,
                         pack_ctrler_state)
    from .kv import (KvConfig, _kv_program, _kv_replay_program,
                     init_kv_cluster, pack_kv_state)
    from .shardkv import (ShardKvConfig, _shardkv_program,
                          init_shardkv_cluster, pack_shardkv_state)
    from .state import init_cluster, pack_state
    from .trace import _traced_program

    key_sds = _S((2,), _U32)

    # ------------------------------------------------------- raft family
    cfg = storm_profiles()["durability"][0]
    cfg_bug = cfg.replace(bug="ack_before_fsync")
    cfg_m = cfg.replace(metrics=True)

    def one_state(c, packed):
        def f(k):
            s = init_cluster(c, k, c.knobs())
            return pack_state(c, s) if packed else s
        return jax.eval_shape(f, key_sds)

    wide1 = one_state(cfg, False)
    packed1 = one_state(cfg, True)
    forbidden = (_dims_of(wide1) | _dims_of(packed1) | _cfg_dims(cfg))
    L = _pick_lanes(forbidden)

    def raft_states(c, packed, n):
        return _batched(one_state(c, packed), n)

    kn = cfg.knobs()
    ccfg = CoverageConfig().fingerprint_key()
    harvest_allow = frozenset({"lane_cumsum", "lane_reduce"})
    cov_allow = frozenset({"lane_scatter"})

    def mesh2():
        return jax.sharding.Mesh(np.array(jax.devices()[:2]),
                                 (engine.CLUSTER_AXIS,))

    specs = [
        ProgramSpec(
            "fuzz.wide", "fuzz",
            lambda: (engine._fuzz_program(cfg.static_key(), L, None, False),
                     (_S((), _I32), kn, _S((), _I32))),
            n_lanes=L, expected_carry=lambda: raft_states(cfg, False, L),
            draw_group="raft.fuzz", golden_leg="clean"),
        ProgramSpec(
            "fuzz.bug", "fuzz",
            lambda: (engine._fuzz_program(cfg_bug.static_key(), L, None,
                                          False),
                     (_S((), _I32), cfg_bug.knobs(), _S((), _I32))),
            n_lanes=L,
            expected_carry=lambda: raft_states(cfg_bug, False, L),
            draw_group="raft.fuzz", golden_leg="bug"),
        ProgramSpec(
            "fuzz.metrics", "fuzz",
            lambda: (engine._fuzz_program(cfg_m.static_key(), L, None,
                                          False),
                     (_S((), _I32), cfg_m.knobs(), _S((), _I32))),
            n_lanes=L, metrics_off=False,
            expected_carry=lambda: raft_states(cfg_m, False, L),
            draw_group="raft.fuzz"),
        ProgramSpec(
            "fuzz.percluster", "fuzz",
            lambda: (engine._fuzz_program(cfg.static_key(), L, None, True),
                     (_S((), _I32), kn.broadcast(L), _S((), _I32))),
            n_lanes=L, expected_carry=lambda: raft_states(cfg, False, L),
            draw_group="raft.fuzz"),
        ProgramSpec(
            "fuzz.sharded", "fuzz",
            lambda: (engine._fuzz_program(cfg.static_key(), L, mesh2(),
                                          False),
                     (_S((), _I32), kn, _S((), _I32))),
            n_lanes=L, expected_carry=lambda: raft_states(cfg, False, L),
            draw_group="raft.fuzz", needs_devices=2),
        ProgramSpec(
            "sweep.uniform_cell", "sweep",
            lambda: (engine._uniform_cell_program(cfg.static_key(), L),
                     (_S((), _I32), kn, _S((), _I32), _S((), _I32))),
            n_lanes=L, expected_carry=lambda: raft_states(cfg, False, L),
            draw_group="raft.fuzz"),
    ]

    for packed in (True, False):
        tag = "packed" if packed else "wide"
        specs += [
            ProgramSpec(
                f"pool.init.{tag}", "pool",
                functools.partial(
                    lambda p: (engine._pool_init_program(
                        cfg.static_key(), L, None, p),
                        (_S((), _I32), kn, _S((), _I32))), packed),
                n_lanes=L, carry_site="out_prefix",
                expected_carry=functools.partial(raft_states, cfg, packed,
                                                 L),
                draw_group="raft.init"),
            ProgramSpec(
                f"pool.chunk.{tag}", "pool",
                functools.partial(
                    lambda p: (engine._chunk_program(cfg.static_key(), L, p),
                               (raft_states(cfg, p, L), _S((L, 2), _U32),
                                kn, _S((), _I32))), packed),
                n_lanes=L,
                expected_carry=functools.partial(raft_states, cfg, packed,
                                                 L),
                draw_group="raft.chunk",
                golden_leg="pool" if packed else None),
            ProgramSpec(
                f"pool.harvest.{tag}", "pool",
                functools.partial(
                    lambda p: (engine._harvest_program(cfg.static_key(), L,
                                                       p),
                               (raft_states(cfg, p, L), _S((L, 2), _U32),
                                _S((L,), _I32), _S((), _I32), _S((), _I32),
                                kn, _S((), _I32))), packed),
                n_lanes=L, carry_site="out_prefix",
                expected_carry=functools.partial(raft_states, cfg, packed,
                                                 L),
                allow=harvest_allow, draw_group="raft.harvest"),
        ]

    specs += [
        ProgramSpec(
            "pool.chunk.metrics", "pool",
            lambda: (engine._chunk_program(cfg_m.static_key(), L, True),
                     (raft_states(cfg_m, True, L), _S((L, 2), _U32),
                      cfg_m.knobs(), _S((), _I32))),
            n_lanes=L, metrics_off=False,
            expected_carry=lambda: raft_states(cfg_m, True, L),
            draw_group="raft.chunk"),
        ProgramSpec(
            "pool.lane_harvest.packed", "pool",
            lambda: (engine._lane_harvest_program(cfg.static_key(), L, None,
                                                  True),
                     (raft_states(cfg, True, L), _S((L, 2), _U32),
                      _S((L,), _I32), _S((L,), _I32), _S((), _I32), kn,
                      _S((), _I32))),
            n_lanes=L, carry_site="out_prefix",
            expected_carry=lambda: raft_states(cfg, True, L),
            allow=harvest_allow, draw_group="raft.harvest"),
        ProgramSpec(
            "pool.unpack_batch", "pool",
            lambda: (engine._unpack_batch_program(cfg.static_key(), L),
                     (raft_states(cfg, True, L),)),
            n_lanes=L, carry_site="out_prefix",
            expected_carry=lambda: raft_states(cfg, False, L)),
        ProgramSpec(
            "cov.chunk.packed", "coverage",
            lambda: (engine._cov_chunk_program(cfg.static_key(), L, ccfg,
                                               True),
                     (raft_states(cfg, True, L), _S((L, 2), _U32),
                      kn.broadcast(L), _S((ccfg.bitmap_bits,), jnp.bool_),
                      _S((L,), _I32), _S((), _I32))),
            n_lanes=L, coverage=True, allow=cov_allow,
            expected_carry=lambda: (
                raft_states(cfg, True, L),
                _S((ccfg.bitmap_bits,), jnp.bool_), _S((L,), _I32)),
            draw_group="raft.chunk"),
        ProgramSpec(
            "cov.harvest.packed", "coverage",
            lambda: (engine._cov_harvest_program(cfg.static_key(), L, ccfg,
                                                 True),
                     (raft_states(cfg, True, L), _S((L, 2), _U32),
                      _S((L,), _I32), kn.broadcast(L), _S((L,), _I32),
                      _S((L,), _I32), _S((ccfg.bitmap_bits,), jnp.bool_),
                      _S((), _I32), _S((), _I32), kn, _S((), _I32))),
            n_lanes=L, coverage=True, carry_site="out_prefix",
            expected_carry=lambda: raft_states(cfg, True, L),
            allow=harvest_allow | cov_allow,
            draw_group="raft.cov_harvest"),
        ProgramSpec(
            "cov.chunk.sharded", "coverage",
            lambda: (engine._cov_chunk_sharded_program(cfg.static_key(), L,
                                                       ccfg, 2, True),
                     (raft_states(cfg, True, L), _S((L, 2), _U32),
                      kn.broadcast(L),
                      _S((2, ccfg.bitmap_bits), jnp.bool_),
                      _S((L,), _I32), _S((), _I32))),
            n_lanes=L, coverage=True, allow=cov_allow,
            expected_carry=lambda: (
                raft_states(cfg, True, L),
                _S((2, ccfg.bitmap_bits), jnp.bool_), _S((L,), _I32)),
            draw_group="raft.chunk"),
        ProgramSpec(
            "cov.harvest.sharded", "coverage",
            lambda: (engine._cov_harvest_sharded_program(
                cfg.static_key(), L, ccfg, mesh2(), True),
                (raft_states(cfg, True, L), _S((L, 2), _U32),
                 _S((L,), _I32), _S((L,), _I32), kn.broadcast(L),
                 _S((L,), _I32), _S((L,), _I32),
                 _S((2, ccfg.bitmap_bits), jnp.bool_),
                 _S((), _I32), kn, _S((), _I32))),
            n_lanes=L, coverage=True, carry_site="out_prefix",
            expected_carry=lambda: raft_states(cfg, True, L),
            allow=harvest_allow | cov_allow,
            draw_group="raft.cov_harvest", needs_devices=2),
    ]

    for packed in (True, False):
        tag = "packed" if packed else "wide"
        specs += [
            ProgramSpec(
                f"replay.{tag}", "replay",
                functools.partial(
                    lambda p: (engine._replay_program(cfg.static_key(), p),
                               (_S((), _I32), kn, _S((), _I32),
                                _S((), _I32))), packed),
                expected_carry=functools.partial(one_state, cfg, packed),
                draw_group="raft.replay"),
            ProgramSpec(
                f"trace.{tag}", "trace",
                functools.partial(
                    lambda p: (_traced_program(cfg.static_key(), 8, p),
                               (_S((), _I32), kn, _S((), _I32))), packed),
                expected_carry=functools.partial(one_state, cfg, packed),
                draw_group="raft.replay"),
        ]

    # ---------------------------------------------------- service layers
    def service_family(prefix, scfg, kcfg, program, replay, init_fn,
                       pack_fn, extra_knobs, group, n_extra_init=()):
        def one(packed):
            def f(k):
                s = init_fn(scfg, kcfg, k, scfg.knobs(), *n_extra_init)
                return pack_fn(scfg, kcfg, s) if packed else s
            return jax.eval_shape(f, key_sds)

        forb = (_dims_of(one(False)) | _dims_of(one(True))
                | _cfg_dims(scfg, kcfg))
        n = _pick_lanes(forb)
        out = []
        for packed in (True, False):
            tag = "packed" if packed else "wide"
            out.append(ProgramSpec(
                f"{prefix}.fuzz.{tag}", prefix,
                functools.partial(
                    lambda p: (program(scfg.static_key(), kcfg, n, None,
                                       False, p),
                               (_S((), _I32), scfg.knobs(), extra_knobs,
                                _S((), _I32))), packed),
                n_lanes=n,
                expected_carry=functools.partial(
                    lambda p: _batched(one(p), n), packed),
                draw_group=group))
        if replay is not None:
            out.append(ProgramSpec(
                f"{prefix}.replay.packed", prefix,
                lambda: (replay(scfg.static_key(), kcfg, True),
                         (_S((), _I32), scfg.knobs(), extra_knobs,
                          _S((), _I32), _S((), _I32))),
                expected_carry=functools.partial(one, True)))
        return out

    svc_cfg = SimConfig()
    kcfg = KvConfig()
    specs += service_family("kv", svc_cfg, kcfg, _kv_program,
                            _kv_replay_program, init_kv_cluster,
                            pack_kv_state, kcfg.knobs(), "kv.fuzz")
    ctcfg = CtrlerConfig()
    specs += service_family("ctrler", svc_cfg, ctcfg, _ctrler_program,
                            _ctrler_replay_program, init_ctrler_cluster,
                            pack_ctrler_state, ctcfg.knobs(), "ctrler.fuzz")
    sk_scfg = SimConfig(n_nodes=3)
    skcfg = ShardKvConfig()
    specs += service_family(
        "shardkv", sk_scfg, skcfg, _shardkv_program, None,
        init_shardkv_cluster, pack_shardkv_state, skcfg.knobs(),
        "shardkv.fuzz", n_extra_init=(skcfg.knobs(),))
    assert len(specs) == REGISTRY_PROGRAMS, (
        f"cached-program count changed: {len(specs)} != "
        f"{REGISTRY_PROGRAMS} — host-side planes (telemetry) must not add "
        f"programs; a deliberate new program updates REGISTRY_PROGRAMS"
    )
    return specs


def golden_guard_legs() -> dict:
    """leg -> [program names]: which golden_fuzz.json legs the registry's
    entries pin. tests/test_trace.py enumerates its guard legs through
    this, so a new program family cannot silently dodge the guards."""
    legs: dict = {}
    for s in registry():
        if s.golden_leg:
            legs.setdefault(s.golden_leg, []).append(s.name)
    return legs


# =========================================================================
# planted defects: each pass's testbed (lint --selftest traces these)
# =========================================================================

def defect_registry() -> list:
    """Four deliberately-broken programs, one per pass class. The lint CLI
    exposes them via ``--selftest`` (expected exit 1) so CI can prove the
    analyzer still catches each defect class without shipping a broken
    production program."""
    L = 6

    def cross_lane():
        def run(x, n):
            # a cluster peeking at its neighbor's state every tick
            return jax.lax.fori_loop(
                0, n, lambda _, c: jnp.roll(c, 1, axis=0) + 1, x)
        return (jax.jit(run),
                (_S((L, 4), jnp.float32), _S((), _I32)))

    def key_reuse():
        def run(seed):
            k = jax.random.PRNGKey(seed)
            # two independent consumers of the SAME key
            return (jax.random.uniform(k, (4,))
                    + jax.random.normal(k, (4,)))
        return jax.jit(run), (_S((), _I32),)

    def metrics_leak():
        def run(t, hist, n):
            # a metrics histogram carried although metrics is OFF
            return jax.lax.fori_loop(
                0, n, lambda _, c: (c[0] + 1, c[1] + 1), (t, hist))
        return (jax.jit(run),
                (_S((L,), _I32), _S((L, 16), _I32), _S((), _I32)))

    def wide_carry():
        def run(x, n):
            # an in-bounds counter (<= 255) carried at i32 instead of u8
            return jax.lax.fori_loop(
                0, n, lambda _, c: (c + 1) % 200, x)
        return jax.jit(run), (_S((L,), _I32), _S((), _I32))

    return [
        ProgramSpec("defect.cross_lane_roll", "defect", cross_lane,
                    n_lanes=L,
                    expected_carry=lambda: _S((L, 4), jnp.float32)),
        ProgramSpec("defect.key_reuse", "defect", key_reuse),
        ProgramSpec("defect.metrics_leak", "defect", metrics_leak,
                    n_lanes=L,
                    expected_carry=lambda: (_S((L,), _I32),
                                            _S((L, 0), _I32))),
        ProgramSpec("defect.wide_carry", "defect", wide_carry,
                    n_lanes=L,
                    expected_carry=lambda: _S((L,), jnp.uint8)),
    ]
