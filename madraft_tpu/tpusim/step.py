"""One lockstep tick of a simulated Raft cluster, as a pure JAX function.

This is the batched re-imagination of the reference's per-node async tick
(/root/reference/src/raft/raft.rs: election timer 260-263, RequestVote fan-out
266-293, RPC handlers 213-233, snapshot path 149-168) plus the simulator
semantics it runs on (SURVEY.md §2.6): per-message loss/latency draws, pairwise
partitions, kill/restart with persistent state, message counting.

Phase order within a tick (this ordering gives persist-before-send for free — all
sends are computed from post-update persistent arrays, mirroring the reference's
"persist after RPC handlers mutate state" rule at raft.rs:224-233 — ONLY under
the historic perfect-persistence model; with the durability axis enabled
(fsync_every > 1 or p_lose_unsynced > 0, state.py durability notes) the
correct algorithm earns it by explicit fsyncs at every state-exposing site,
and the planted "ack_before_fsync" bug strips the handler-reply ones):

  1. faults     — crash / restart / repartition draws; a crash drops the
                  un-fsynced suffix with p_lose_unsynced (rollback to the
                  durable_len / durable_term / durable_voted_for watermark)
  2. deliver    — ONE message per (destination, mailbox type) per tick,
                  vectorized over destinations: when several sources are due
                  at the same destination the tick-rotated minimum source
                  wins and the rest defer one tick (round-robin, so no source
                  starves). Raft tolerates the deferral — every delivery
                  field is cumulative — and it turns the per-source
                  sequential passes (the measured hot spot at 16k-cluster
                  batches) into single vectorized ones. Order: RV/AE
                  RESPONSES first (request processing overwrites response
                  slots, so responses must be consumed before requests or
                  deterministic delays starve them — see the RV-responses
                  comment), then install-snapshot triggers, then RV/AE
                  requests.
  3. timers     — election timeouts -> candidacy + RequestVote broadcast;
                  client command injection at leaders; leader heartbeat ->
                  AppendEntries (or install-snapshot for peers behind the
                  leader's snapshot boundary) with entries from next_idx
  4. commit     — leader advances commit via majority-match (current-term rule)
  5. oracle     — safety invariant reductions (election safety, log matching,
                  commit durability) + liveness/stat bookkeeping
  6. compact    — advance the snapshot boundary (commit, or the service
                  layer's apply cursor); a pure index bump, no data movement
  7. fsync      — background durability: each node syncs its persistent
                  state every fsync_every ticks (staggered); 1 = the
                  historic always-durable model

The coverage subsystem (coverage.py) fingerprints the POST-tick state this
function returns — its abstract-state code (state.abstract_node_tuple) is a
pure observation computed outside this function by the engine's coverage
chunk program, so the tick itself carries zero coverage cost and its traced
program (and every cached executable) is byte-identical with coverage off.

The log is a CANONICAL RING (see state.py): absolute (1-based) index ``a``
always lives in lane ``(a - 1) & (cap - 1)``; ``base`` (snapshot boundary) and
``log_len``/``commit``/next/match indices are absolute, and the live window is
``(base, base + cap]``. Because the lane of an index never changes, compaction
and install-snapshot are pure ``base`` bumps — no shifting, ever — and every
lookup is a one-hot lane select. This layout exists because TPU hates per-row
dynamic indexing: gathers/scatters with row-varying indices serialize on the
scalar core (measured ~16 ms per op at a 4k-cluster batch in the round-1
design), while one-hot selects and masked writes are pure VPU work.
Control-flow divergence across the batch is handled with masked updates
(`jnp.where`); loops are only over the (static, tiny) node and entry-batch
axes, so XLA sees fully static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from madraft_tpu.tpusim.config import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    NOOP_CMD,
    SimConfig,
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
    VIOLATION_PREFIX_DIVERGE,
)
from madraft_tpu.tpusim.config import LATENCY_PHASES
from madraft_tpu.tpusim.metrics import fold_latencies, fold_phases, update_worst
from madraft_tpu.tpusim.state import (
    ClusterState,
    I32,
    PackedClusterState,
    pack_state,
    unpack_state,
)

_BIG = 1 << 30  # sentinel above any absolute log index


def step_cluster_packed(
    cfg: SimConfig, p: PackedClusterState, cluster_key: jax.Array, kn=None
) -> PackedClusterState:
    """One tick over the PACKED carry (ISSUE 9): widen-on-use at this
    boundary — unpack to the wide i32 layout, run the identical
    step_cluster, pack the result. The arithmetic below never sees a
    narrow dtype, so the trajectory is bit-identical to the wide carry
    whenever pack/unpack round-trips exactly (state.py packed schema
    notes); only what the loop CARRIES — the HBM-resident share — shrinks."""
    return pack_state(cfg, step_cluster(cfg, unpack_state(cfg, p), cluster_key, kn))

# Raft-tick PRNG block id (kv.py/shardkv.py fold their own disjoint ids).
_S_STEP_BLOCK = 0


class _DrawBlock:
    """All of a tick's randomness from ONE threefry call.

    Per-site `fold_in`+`split`+draw calls have a fixed per-call cost that
    dominated ~15% of the tick at 16k-cluster batches (measured: dropping a
    single redundant [n,n] draw pair was worth +7%). Instead, one
    `jax.random.bits` of the tick's full u32 budget is sliced STATICALLY in a
    fixed order — same determinism contract (a pure function of the key),
    one PRNG invocation.

    randint uses a fixed-point multiply-shift (floor(u01 * span)) instead of
    `draw % span`: with TRACED spans (dynamic knobs are per-cluster runtime
    arrays) an integer modulo lowers to a division sequence, which measured
    ~2.7x on the whole tick; the multiply-shift is one VPU multiply. Bias is
    <= span/2^24 (vs span/2^32 for modulo) — negligible for the tick-scale
    spans here, and the uniformity class is unchanged.
    """

    def __init__(self, key: jax.Array, total: int):
        self.bits = jax.random.bits(key, (total,))  # uint32
        self.off = 0

    def _take(self, shape):
        size = 1
        for d in shape:
            size *= d
        out = self.bits[self.off:self.off + size].reshape(shape)
        self.off += size
        return out

    @staticmethod
    def _u01(words):
        """u32 words -> exact f32 uniforms in [0, 1): the draw keeps 24 bits
        so the conversion is exact and u < 1.0 always holds — p=1.0 knobs
        (deterministic schedules for oracle validation) fire every tick,
        with no round-up-to-1.0 corner. Single source of the treatment for
        bern/uniform/_net_draws."""
        return (words >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)

    def bern(self, p, shape):
        # p may be a traced f32 scalar (dynamic knob); compare in [0,1) space
        return self._u01(self._take(shape)) < p

    def bern_w(self, p, shape):
        """bern PLUS the raw threefry words: bits 8..31 decide the draw
        (via _u01); bits 0..7 are FREE for the caller — the _net_draws
        packing idiom (disjoint bit ranges of one word are independent
        draws). The gray-failure axes (ISSUE 19) harvest these low bytes,
        so they add ZERO to the tick's PRNG budget and leave every
        neutral-knob trajectory bit-identical."""
        w = self._take(shape)
        return self._u01(w) < p, w

    def randint(self, lo, hi, shape):  # [lo, hi); bounds may be traced i32
        val, _ = self.randint_w(lo, hi, shape)
        return val

    def randint_w(self, lo, hi, shape):
        """randint PLUS the raw words (low byte free — see bern_w)."""
        w = self._take(shape)
        span = (jnp.asarray(hi, I32) - jnp.asarray(lo, I32)).astype(jnp.float32)
        # floor(u01 * span): u01 < 1.0 exactly (see _u01), so the result is
        # always in [0, span). No integer division anywhere.
        return (jnp.asarray(lo, I32)
                + jnp.floor(self._u01(w) * span).astype(I32)), w

    def uniform(self, shape):
        return self._u01(self._take(shape))


def _bern8(words: jax.Array, p) -> jax.Array:
    """Bernoulli(p) at 8-bit resolution from the FREE low byte of already-
    consumed threefry words (the suffix-loss idiom in the faults phase):
    same bias class as the _net_draws delay byte."""
    return (words & 0xFF).astype(jnp.float32) * jnp.float32(2.0 ** -8) < p


def _randint8(words: jax.Array, lo, span) -> jax.Array:
    """lo + floor(low_byte/256 * span) from free low bytes: uniform over
    [lo, lo + span - 1] for span >= 1 (multiply-shift, no division —
    the _net_draws delay treatment). Callers gate span >= 1."""
    s = jnp.maximum(jnp.asarray(span, I32), 0).astype(jnp.uint32)
    return jnp.asarray(lo, I32) + (((words & 0xFF) * s) >> 8).astype(I32)


def _block_total(n: int) -> int:
    # faults 4n+3 (crash/restart/colors/restart-timers + u_part + asym pair),
    # three timer resets 3n, rv/ae response nets 2n, election timers n,
    # client n, three [n,n] send nets — every (delay, lost) pair packs into
    # ONE u32 (see _net_draws), which nearly halves the threefry budget.
    # (the suffix-loss draw rides the free low byte of the color words —
    # no budget of its own)
    return 11 * n + 3 + 3 * n * n


def _timeout_draw(kn, blk: "_DrawBlock", shape, skew) -> jax.Array:
    """Election-timeout redraw: the base [eto_min, eto_max] window plus
    the node's persistent gray clock-skew offset (me * eto_skew; ISSUE 19
    — 0 at the neutral knob, leaving the draw bit-identical). Every call
    site is a per-node (n,) draw, so the offset applies elementwise."""
    return blk.randint(kn.eto_min, kn.eto_max + 1, shape) + skew


def _net_draws(kn, blk: "_DrawBlock", shape):
    """(delay, lost) draws for a batch of sends, packed into ONE u32 per
    send: bits 8..31 decide loss (via _u01 — exact, < 1.0), bits 0..7
    decide the delay via multiply-shift ((w & 0xFF) * span) >> 8 — the exact
    same bias class (<= span/256) as the former modulo, with no integer
    division (traced-span modulo was the measured dynamic-knob cliff; see
    _DrawBlock). Spans wider than 256 are clamped so every value stays
    drawable rather than silently truncating the regime. Disjoint bit ranges
    of one threefry word are independent draws."""
    w = blk._take(shape)
    lost = blk._u01(w) < kn.loss_prob
    span = jnp.clip(
        jnp.asarray(kn.delay_max, I32) + 1 - jnp.asarray(kn.delay_min, I32),
        1, 256,
    ).astype(jnp.uint32)
    delay = jnp.asarray(kn.delay_min, I32) + (((w & 0xFF) * span) >> 8).astype(I32)
    return delay, lost


def _slot(abs_idx: jax.Array, cap: int) -> jax.Array:
    """Canonical lane of absolute (1-based) index abs_idx: (a-1) mod cap."""
    return (abs_idx - 1) & (cap - 1)


def _lane_abs(base: jax.Array, cap: int) -> jax.Array:
    """Absolute index each lane holds for a window anchored at ``base``:
    the unique a in (base, base+cap] with (a-1) mod cap == lane."""
    k = jnp.arange(cap, dtype=I32)
    return base[..., None] + ((k - base[..., None]) & (cap - 1)) + 1


def _row_gather(arr: jax.Array, pos: jax.Array, cap: int) -> jax.Array:
    """arr[..., i, pos[..., i]] as a one-hot mask-reduce over the lane axis.

    Per-row dynamic-index gathers serialize on the TPU scalar core (measured
    ~16 ms per call at a 4k-cluster batch — the round-1 perf cliff); the
    one-hot form is pure elementwise + lane reduction. Callers mask invalid
    positions.
    """
    oh = jnp.arange(cap, dtype=I32) == jnp.clip(pos, 0, cap - 1)[..., None]
    return jnp.sum(jnp.where(oh, arr, 0), axis=-1)


def _entry_mix(term: jax.Array, val: jax.Array, abs_idx: jax.Array) -> jax.Array:
    """Position-sensitive entry hash whose XOR-fold is order-free, so a batch
    of entries crossing a compaction boundary folds in one vectorized pass
    (no sequential loop). Any two histories differing in a compacted entry's
    (term, value, index) diverge with overwhelming probability."""
    h = (val ^ (abs_idx * jnp.int32(-1640531527))) * jnp.int32(-2048144789)
    return h ^ (term * jnp.int32(-1028477387))


def _term_at(log_term, snap_term, base, abs_idx, cap):
    """Term of absolute (1-based) index abs_idx per node; snap_term at the
    boundary itself. Callers mask positions outside (base, log_len]."""
    return jnp.where(
        abs_idx <= base, snap_term, _row_gather(log_term, _slot(abs_idx, cap), cap)
    )


def step_cluster(
    cfg: SimConfig, s: ClusterState, cluster_key: jax.Array, kn=None
) -> ClusterState:
    if kn is None:  # single-config callers: bake the knobs as constants
        kn = cfg.knobs()
    n, cap, ae_max = cfg.n_nodes, cfg.log_cap, cfg.ae_max
    # metrics plane (ISSUE 10): pre-tick baselines for the per-lane event
    # counters. Captured before the suffix-loss rollback below, so a bump
    # is counted NET of any rollback this tick (a crash-lowered term that
    # climbs back to its old value is not a bump).
    term0, commit0 = s.term, s.commit
    t = s.tick + 1  # messages sent at tick t-1 with delay 1 arrive now
    key = jax.random.fold_in(cluster_key, t)
    blk = _DrawBlock(jax.random.fold_in(key, _S_STEP_BLOCK), _block_total(n))
    me = jnp.arange(n, dtype=I32)
    eye = jnp.eye(n, dtype=jnp.bool_)
    # gray clock skew (ISSUE 19): node i's election window is offset by
    # i * eto_skew at every timeout redraw (and at init) — 0 = neutral
    skew = me * jnp.asarray(kn.eto_skew, I32)

    # ------------------------------------------------------------------ faults
    # Rolling restart waves (ISSUE 19): a DETERMINISTIC staggered
    # schedule, not a draw. Wave w covers ticks [w*P, (w+1)*P) and takes
    # node (w mod n) down for its first rolling_down ticks; the node is
    # forced back up when its window ends. rolling_period=0 leaves every
    # mask False (neutral — and the knobs consume no PRNG words).
    rp = jnp.maximum(jnp.asarray(kn.rolling_period, I32), 1)
    wave = t // rp
    wave_i = wave - ((wave - me) % n)  # node i's latest assigned wave
    age = t - wave_i * rp              # ticks since that wave started
    roll_on = kn.rolling_period > 0
    roll_sched = roll_on & (wave_i >= 0)
    roll_down = roll_sched & (age < kn.rolling_down)
    roll_up = roll_sched & (age == kn.rolling_down)

    restart_draw, w_restart = blk.bern_w(kn.p_restart, (n,))
    # a scheduled-down node may not restart early; a wave-end node is
    # forced up (its Bernoulli draw is overridden, not consumed extra)
    restart = (~s.alive) & ((restart_draw & ~roll_down) | roll_up)
    crash_draw, w_crash = blk.bern_w(kn.p_crash, (n,))
    crash_bern = s.alive & crash_draw
    # Keep a quorum-capable cluster: at most max_dead simultaneously-dead nodes.
    dead_after_restart = jnp.sum((~s.alive) & (~restart))
    budget = kn.max_dead - dead_after_restart
    # scheduled kills BYPASS the budget: a game-day drill does not respect
    # the fault budget (that is the point of the drill)
    crash = (crash_bern & (jnp.cumsum(crash_bern.astype(I32)) <= budget)) \
        | (s.alive & roll_down)
    alive = (s.alive | restart) & ~crash

    # Restart = recovery from persisted state (term/voted_for/log/base survive;
    # the volatile set resets — raft.rs:194-211 restore(), tester.rs:284-327).
    # The snapshot covers 1..base, so commit restarts at base, not 0.
    role = jnp.where(restart, FOLLOWER, s.role)
    if cfg.bug == "forget_voted_for":
        # planted bug: votedFor not persisted — a restarted node may re-vote
        # in a term it already voted in (two leaders share the term; the
        # election-safety oracle must fire). config.py RAFT_BUGS.
        s = s._replace(voted_for=jnp.where(restart, -1, s.voted_for))
    rst_tmr, w_rst_tmr = blk.randint_w(kn.eto_min, kn.eto_max + 1, (n,))
    timer = jnp.where(restart, rst_tmr + skew, s.timer)
    hb = jnp.where(restart, 0, s.hb)
    commit = jnp.where(restart, s.base, s.commit)
    compact_floor = jnp.where(restart, s.base, s.compact_floor)
    votes = jnp.where(restart[:, None], False, s.votes)
    next_idx = jnp.where(restart[:, None], 1, s.next_idx)
    match_idx = jnp.where(restart[:, None], 0, s.match_idx)

    # Limping nodes (ISSUE 19): an alive node enters a limp with p_limp,
    # multiplying ALL its send delays by a factor drawn in
    # [2, limp_mult_max] (redrawn per episode); it heals with p_limp_heal,
    # and a restart always clears it (fresh process). Every draw rides the
    # FREE low bytes of words consumed above (crash draw -> onset,
    # restart draw -> multiplier, restart-timer draw -> heal): zero extra
    # PRNG budget, bit-identical at the neutral knobs.
    limp_on = alive & (kn.limp_mult_max >= 2) & _bern8(w_crash, kn.p_limp)
    limp_mult = _randint8(w_restart, 2, kn.limp_mult_max - 1)
    limp = jnp.where(
        restart, 1,
        jnp.where(
            limp_on, limp_mult,
            jnp.where(_bern8(w_rst_tmr, kn.p_limp_heal), 1, s.limp),
        ),
    )

    # Partition schedule, one mutually-exclusive event per tick drawn from a
    # single uniform: random symmetric 2-coloring (connect2/disconnect2
    # masks, /root/reference/src/kvraft/tester.rs:88-124), full heal,
    # leader-in-minority partition (the current leader plus its successor
    # against the rest — tester.rs:184-191's targeted cut), or an
    # ASYMMETRIC single-link cut (one directed (src -> dst) edge down; the
    # adj tensor is [dst, src] = "messages from src reach dst", so one-sided
    # failures the reference models via connect/disconnect are first-class).
    # Asymmetric cuts accumulate until the next repartition/heal event.
    u_part = blk.uniform(())
    # The coloring tests bits 8..31 (_u01); bits 0..7 of the same words are
    # free and carry the suffix-loss draw below — the _net_draws packing
    # idiom (disjoint bit ranges of one threefry word are independent
    # draws), so the new fault axis leaves the legacy draw layout — and
    # with it every recorded (seed, cluster) trajectory and tuned storm —
    # bit-identical.
    w_colors = blk._take((n,))
    colors = _DrawBlock._u01(w_colors) < 0.5
    asym_dst = blk.randint(0, n, ())
    asym_off = blk.randint(1, n, ())  # src = dst + off mod n, never == dst
    part_adj = colors[:, None] == colors[None, :]
    th1 = kn.p_repartition
    th2 = th1 + kn.p_heal
    th3 = th2 + kn.p_leader_part
    th4 = th3 + kn.p_asym_cut
    do_part = u_part < th1
    do_heal = (~do_part) & (u_part < th2)
    lead_pre = alive & (s.role == LEADER)
    lid = jnp.argmax(lead_pre).astype(I32)  # first live leader (0 if none)
    lcol = (me == lid) | (me == (lid + 1) % n)
    lpart_adj = lcol[:, None] == lcol[None, :]
    do_lpart = (u_part >= th2) & (u_part < th3) & jnp.any(lead_pre)
    do_asym = (u_part >= th3) & (u_part < th4)
    cut = (me[:, None] == asym_dst) & (me[None, :] == (asym_dst + asym_off) % n)
    adj = (
        jnp.where(
            do_part, part_adj,
            jnp.where(
                do_heal, True,
                jnp.where(do_lpart, lpart_adj, s.adj & ~(cut & do_asym)),
            ),
        )
        | eye
    )

    # Lossy persistence (the madsim `fs` axis; state.py durability notes):
    # a crash drops the un-fsynced suffix with p_lose_unsynced — the log
    # rolls back to the durable watermark and term/voted_for to their
    # fsynced shadows (an atomic pair: both live in the one state file the
    # last fsync wrote). Applied AT CRASH, not restart: in-flight AE
    # deliveries read the sender's live ring (read-at-delivery), so a dead
    # node's lost suffix must already be gone. Ring lanes beyond the rolled
    # watermark keep their bytes — every reader masks by log_len (the
    # commit-shadow loop reads up to the stale volatile `commit`, whose
    # lanes are exactly the pre-crash bytes it already matched). The draw
    # rides bits 0..7 of the color words (see above): 8-bit resolution,
    # the same bias class as the _net_draws delay byte.
    lose = crash & (
        (w_colors & 0xFF).astype(jnp.float32) * jnp.float32(2.0 ** -8)
        < kn.p_lose_unsynced
    )
    s = s._replace(
        term=jnp.where(lose, s.durable_term, s.term),
        voted_for=jnp.where(lose, s.durable_voted_for, s.voted_for),
        # durable_len >= base always (compaction/install fsync through the
        # boundary), so the rolled-back window stays legal
        log_len=jnp.where(lose, s.durable_len, s.log_len),
    )

    term, voted_for = s.term, s.voted_for
    log_term, log_val, log_len = s.log_term, s.log_val, s.log_len
    log_tick = s.log_tick  # metrics submit stamps ride with the log
    base, snap_term, prefix_hash = s.base, s.snap_term, s.prefix_hash
    durable_len = s.durable_len
    durable_term, durable_voted_for = s.durable_term, s.durable_voted_for
    # Fsync sites below (correct algorithm): persist-before-reply at the
    # RV/AE handlers (raft.rs:224-233), persist at election start
    # (raft.rs:248), persist at leader append (start(), raft.rs:311-313 —
    # the leader's own log_len is commit-counted, so it must be durable),
    # persist at install-snapshot and compaction (cond_install_snapshot /
    # snapshot()), plus the background fsync_every cadence at tick end.
    # bug == "ack_before_fsync" strips exactly the two HANDLER syncs.
    rv_rsp_t, rv_rsp_term, rv_rsp_granted = s.rv_rsp_t, s.rv_rsp_term, s.rv_rsp_granted
    ae_rsp_t, ae_rsp_term = s.ae_rsp_t, s.ae_rsp_term
    ae_rsp_success, ae_rsp_match = s.ae_rsp_success, s.ae_rsp_match
    delivered = jnp.asarray(0, I32)
    snap_installed_src = jnp.full((n,), -1, I32)
    snap_installed_len = jnp.zeros((n,), I32)
    snap_install_count = s.snap_install_count

    # Delivery selection: among sources due at a destination, the
    # tick-rotated minimum-priority source wins; the rest defer one tick.
    # Every delivery re-checks the link: simcore draws loss/latency at send
    # but re-validates link_up at delivery (simcore.h call_timeout), so a
    # message in flight across a partition that formed after the send is
    # dropped on both backends — required for the differential replay bridge.
    p_src = (me + t) % n  # round-robin priority, [src]

    def pick_one(mail_t, extra_ok=True):
        """-> (pick [dst,src] one-hot, deferred mask, got [dst])."""
        due = (mail_t == t) & alive[:, None]
        ok = due & adj & extra_ok
        pmask = jnp.where(ok, p_src[None, :], n)
        pick = ok & (p_src[None, :] == jnp.min(pmask, axis=1)[:, None])
        return pick, ok & ~pick, due

    def picked(pick, field):
        """field value of the picked source per dst (0 where none)."""
        return jnp.sum(jnp.where(pick, field, 0), axis=1)

    # ---------------------------------------------------- deliver: RV responses
    # RESPONSES deliver BEFORE REQUESTS on purpose: processing a request
    # writes a fresh response into the single-slot mailbox (stamped
    # t + delay), so with deterministic or pipelined delays a request
    # arriving every tick would re-stamp the slot into the future every
    # tick and the due response would NEVER be consumed — a response-
    # starvation livelock (match_idx frozen, zero commits) that the default
    # randomized 1..3-tick delays masked with gaps. Consuming due responses
    # first makes the overwrite land on an already-consumed slot. (Requests
    # don't need this: their sends happen in the timer/heartbeat phases,
    # after all deliveries.)
    pick, defer, due = pick_one(rv_rsp_t)
    stale = rv_rsp_t <= t  # includes this tick's processed/dropped slots
    rv_rsp_t = jnp.where(defer, t + 1, jnp.where(stale, 0, rv_rsp_t))
    got = jnp.any(pick, axis=1)
    d_rv_rsp = jnp.sum(pick, dtype=I32)
    delivered += d_rv_rsp
    mterm = picked(pick, rv_rsp_term)
    higher = got & (mterm > term)
    term = jnp.where(higher, mterm, term)
    role = jnp.where(higher, FOLLOWER, role)
    voted_for = jnp.where(higher, -1, voted_for)
    accept = (
        got & jnp.any(pick & rv_rsp_granted, axis=1)
        & (role == CANDIDATE) & (mterm == term)
    )
    votes = votes | (pick & accept[:, None])

    # ---------------------------------------------------- deliver: AE responses
    pick, defer, due = pick_one(ae_rsp_t)
    stale = ae_rsp_t <= t
    ae_rsp_t = jnp.where(defer, t + 1, jnp.where(stale, 0, ae_rsp_t))
    got = jnp.any(pick, axis=1)
    d_ae_rsp = jnp.sum(pick, dtype=I32)
    delivered += d_ae_rsp
    mterm = picked(pick, ae_rsp_term)
    higher = got & (mterm > term)
    term = jnp.where(higher, mterm, term)
    role = jnp.where(higher, FOLLOWER, role)
    voted_for = jnp.where(higher, -1, voted_for)
    okl = got & (role == LEADER) & (mterm == term)
    succ_flag = jnp.any(pick & ae_rsp_success, axis=1)
    succ = okl & succ_flag
    fail = okl & ~succ_flag
    m = picked(pick, ae_rsp_match)
    match_idx = jnp.where(
        pick & succ[:, None],
        jnp.maximum(match_idx, m[:, None]), match_idx,
    )
    next_idx = jnp.where(
        pick & succ[:, None],
        jnp.maximum(next_idx, m[:, None] + 1),
        jnp.where(
            pick & fail[:, None],
            jnp.maximum(jnp.minimum(next_idx, m[:, None] + 1), 1),
            next_idx,
        ),
    )

    # ------------------------------------------- deliver: install-snapshot
    # Payload (boundary, snapshot term, service state) is the sender's live
    # snapshot at delivery; a dead sender = a lost message (state.py
    # rationale). The message's LEADER term deposes stale leaders exactly
    # like AE/RV traffic, and only the current term's leader may install.
    pick, defer, due = pick_one(s.sn_req_t, extra_ok=alive[None, :])
    # clear every slot due this tick (processed, dropped, or dst dead)
    sn_req_t = jnp.where((s.sn_req_t == t) & ~defer, 0, s.sn_req_t)
    sn_req_t = jnp.where(defer, t + 1, sn_req_t)
    got = jnp.any(pick, axis=1)
    d_sn = jnp.sum(pick, dtype=I32)
    delivered += d_sn
    mterm = picked(pick, s.sn_req_term)
    higher = got & (mterm > term)
    term = jnp.where(higher, mterm, term)
    role = jnp.where(higher, FOLLOWER, role)
    voted_for = jnp.where(higher, -1, voted_for)
    acc = got & (mterm == term)
    role = jnp.where(acc & (role == CANDIDATE), FOLLOWER, role)
    # current-leader contact resets the election timer (low bytes of the
    # draw words carry the gray fsync-stall ONSET — see the fsync phase)
    snap_tmr, w_snap_tmr = blk.randint_w(kn.eto_min, kn.eto_max + 1, (n,))
    timer = jnp.where(acc, snap_tmr + skew, timer)
    slen = picked(pick, jnp.broadcast_to(s.base[None, :], (n, n)))
    sterm_snap = picked(pick, jnp.broadcast_to(s.snap_term[None, :], (n, n)))
    # cond_install (raft.rs:153): ignore a snapshot behind our commit.
    inst = acc & (slen > commit)
    # Keep a matching suffix (conditional install); otherwise discard the
    # log. Ring lanes never move — `base` just jumps; if slen is outside
    # our window (> base + cap) then log_len > slen is impossible and the
    # discard branch empties the log anyway.
    keep = inst & (log_len > slen) & (
        _term_at(log_term, snap_term, base, slen, cap) == sterm_snap
    )
    log_len = jnp.where(inst, jnp.where(keep, log_len, slen), log_len)
    base = jnp.where(inst, slen, base)
    snap_term = jnp.where(inst, sterm_snap, snap_term)
    # adopt the sender's compacted-prefix hash with its boundary (atomic pair)
    prefix_hash = jnp.where(
        inst,
        picked(pick, jnp.broadcast_to(s.prefix_hash[None, :], (n, n))),
        prefix_hash,
    )
    commit = jnp.where(inst, jnp.maximum(commit, slen), commit)
    compact_floor = jnp.where(inst, slen, compact_floor)
    # install persists everything (cond_install_snapshot -> persist()):
    # base/snap_term/prefix_hash stay durable by construction
    durable_len = jnp.where(inst, log_len, durable_len)
    durable_term = jnp.where(inst, term, durable_term)
    durable_voted_for = jnp.where(inst, voted_for, durable_voted_for)
    src_id = picked(pick, jnp.broadcast_to(me[None, :], (n, n)))
    snap_installed_src = jnp.where(inst, src_id, snap_installed_src)
    snap_installed_len = jnp.where(inst, slen, snap_installed_len)
    snap_install_count += jnp.sum(inst, dtype=I32)

    # Absolute index held by each lane of each node's ring; `base` is stable
    # from here until compaction (which runs after every consumer).
    abs_arr = _lane_abs(base, cap)  # [n, cap]

    # ----------------------------------------------------- deliver: RV requests
    pick, defer, due = pick_one(s.rv_req_t)
    rv_req_t = jnp.where((s.rv_req_t == t) & ~defer, 0, s.rv_req_t)
    rv_req_t = jnp.where(defer, t + 1, rv_req_t)
    got = jnp.any(pick, axis=1)
    d_rv_req = jnp.sum(pick, dtype=I32)
    delivered += d_rv_req
    mterm = picked(pick, s.rv_req_term)
    higher = got & (mterm > term)
    term = jnp.where(higher, mterm, term)
    role = jnp.where(higher, FOLLOWER, role)
    voted_for = jnp.where(higher, -1, voted_for)
    my_llt = jnp.where(
        log_len > base, _row_gather(log_term, _slot(log_len, cap), cap), snap_term
    )
    cand_llt = picked(pick, s.rv_req_llt)
    cand_lli = picked(pick, s.rv_req_lli)
    log_ok = (cand_llt > my_llt) | ((cand_llt == my_llt) & (cand_lli >= log_len))
    if cfg.bug == "grant_any_vote":
        # planted bug: skip the §5.4.1 up-to-date check — a stale-log
        # candidate can win and overwrite committed entries (commit-shadow
        # oracle must fire). config.py RAFT_BUGS.
        log_ok = jnp.ones_like(log_ok)
    src_id = picked(pick, jnp.broadcast_to(me[None, :], (n, n)))
    grant = got & (mterm == term) & (
        (voted_for == -1) | (voted_for == src_id)
    ) & log_ok
    voted_for = jnp.where(grant, src_id, voted_for)
    # (low bytes of the grant-timer words carry the gray fsync-stall
    # DURATION draw — see the fsync phase)
    grant_tmr, w_grant_tmr = blk.randint_w(kn.eto_min, kn.eto_max + 1, (n,))
    timer = jnp.where(grant, grant_tmr + skew, timer)
    if cfg.bug != "ack_before_fsync":
        # persist-before-reply (raft.rs:224-233): the response exposes
        # term and (via the grant) voted_for — fsync them first. Under the
        # planted bug the reply leaves from volatile state: a voter can
        # grant, crash, revert its vote, and re-grant the term to a rival.
        durable_term = jnp.where(got, term, durable_term)
        durable_voted_for = jnp.where(got, voted_for, durable_voted_for)
        durable_len = jnp.where(got, log_len, durable_len)
    delay, lost = _net_draws(kn, blk, (n,))
    delay = delay * limp  # gray limp: the VOTER is the sender (ISSUE 19)
    send = got & ~lost  # per voter (one response per tick)
    # response slot [candidate, voter] <- the picked (voter, candidate) pair
    resp = pick.T & send[None, :]
    rv_rsp_t = jnp.where(resp, (t + delay)[None, :], rv_rsp_t)
    rv_rsp_term = jnp.where(resp, term[None, :], rv_rsp_term)
    rv_rsp_granted = jnp.where(resp, grant[None, :], rv_rsp_granted)

    # ----------------------------------------------------- deliver: AE requests
    # Entry payloads are read from the SENDER's live log at delivery (the
    # same read-at-delivery model the install-snapshot path uses). This is
    # the round-3 perf redesign: the send-side per-(dst, src) entry gather
    # materialized a [n, n, ae_max, cap] one-hot and two [n, n, ae_max]
    # mailbox tensors — the measured top phase cost. Reading at delivery
    # folds over the ONE picked source per destination, so the gather is
    # [dst, cap] + per-entry [dst, cap] one-hots, and the entry mailboxes
    # vanish from the state entirely. Safety is unchanged: any (index, term,
    # value) triple present in a node's ring at delivery was minted by that
    # term's leader at that index, so delivering it preserves log matching;
    # if the sender compacted past prev mid-flight the message degrades to a
    # heartbeat (it would have sent an install-snapshot by now), and if its
    # log shrank (conflict truncation) the batch tail is dropped — both are
    # valid AppendEntries a correct sender could have sent.
    lane = jnp.arange(cap, dtype=I32)[None, :]
    pick, defer, due = pick_one(s.ae_req_t)
    ae_req_t = jnp.where((s.ae_req_t == t) & ~defer, 0, s.ae_req_t)
    ae_req_t = jnp.where(defer, t + 1, ae_req_t)
    got = jnp.any(pick, axis=1)
    d_ae_req = jnp.sum(pick, dtype=I32)
    delivered += d_ae_req
    mterm = picked(pick, s.ae_req_term)
    higher = got & (mterm > term)
    term = jnp.where(higher, mterm, term)
    role = jnp.where(higher, FOLLOWER, role)
    voted_for = jnp.where(higher, -1, voted_for)
    acc = got & (mterm == term)  # AppendEntries from the current-term leader
    role = jnp.where(acc & (role == CANDIDATE), FOLLOWER, role)
    timer = jnp.where(acc, _timeout_draw(kn, blk, (n,), skew), timer)
    prev = picked(pick, s.ae_req_prev)
    mprev_term = picked(pick, s.ae_req_prev_term)
    # prev at-or-below our snapshot boundary is committed => matches by
    # definition; otherwise the terms must agree (log-matching check).
    prev_ok = (prev <= log_len) & (
        (prev <= base)
        | (_term_at(log_term, snap_term, base, prev, cap) == mprev_term)
    )
    success = acc & prev_ok
    # the picked sender's log, base, and length AT DELIVERY
    plog_t = jnp.sum(jnp.where(pick[:, :, None], log_term[None, :, :], 0), axis=1)
    plog_v = jnp.sum(jnp.where(pick[:, :, None], log_val[None, :, :], 0), axis=1)
    psrc_base = picked(pick, jnp.broadcast_to(base[None, :], (n, n)))
    psrc_len = picked(pick, jnp.broadcast_to(log_len[None, :], (n, n)))
    psrc_snap_term = picked(pick, jnp.broadcast_to(snap_term[None, :], (n, n)))
    # The (prev_term, entries) pair must describe ONE consistent log — the
    # AE induction (receiver@prev term == sender@prev term => identical
    # prefixes => appending the sender's suffix preserves log matching)
    # breaks if prev_term was probed on the send-time log but entries come
    # from a delivery-time log that was meanwhile overwritten by a newer
    # leader. So the sender's CURRENT term at prev must still equal the
    # message's prev_term; otherwise the message degrades to a heartbeat
    # (0 entries), like the compacted-past-prev case.
    cur_prev_term = jnp.where(
        prev == psrc_base,
        psrc_snap_term,
        jnp.sum(jnp.where(lane == _slot(prev, cap)[:, None], plog_t, 0), axis=-1),
    )
    prev_still = (
        (psrc_base <= prev) & (prev <= psrc_len) & (cur_prev_term == mprev_term)
    )
    # effective batch: prev re-validation failed or compacted-past-prev =>
    # heartbeat; sender log shrunk => tail drop. Always contiguous from prev+1.
    nent = jnp.where(
        prev_still,
        jnp.clip(jnp.minimum(picked(pick, s.ae_req_n), psrc_len - prev), 0, ae_max),
        0,
    )
    # Entries of one batch occupy DISTINCT lanes (consecutive absolute
    # indices, nent <= ae_max <= cap), so reads never alias writes within
    # the batch and the whole batch applies in ONE vectorized pass over the
    # log arrays instead of ae_max sequential read-modify-write passes
    # (the log arrays are the largest state; round-3 perf).
    e_ar = jnp.arange(ae_max, dtype=I32)
    abs_e = prev[:, None] + e_ar[None, :] + 1         # [n, e]
    # In-window = (base, base + cap]: below-base entries are already
    # snapshot-covered (their lane holds a live higher index), above
    # base+cap would clobber a live lane (modeled as message-tail drop).
    in_batch = (
        success[:, None] & (e_ar[None, :] < nent[:, None])
        & (abs_e > base[:, None]) & (abs_e <= (base + cap)[:, None])
    )
    if cfg.bug == "no_truncate":
        # planted bug: append only past the end — a conflicting suffix is
        # never overwritten or truncated (log-matching oracle must fire).
        # config.py RAFT_BUGS.
        in_batch = in_batch & (abs_e > log_len[:, None])
    # the canonical ring makes the sender read lane and the receiver write
    # lane the SAME mask — one one-hot serves both
    slot_oh = lane[:, None, :] == _slot(abs_e, cap)[..., None]  # [n, e, cap]
    ent_t = jnp.sum(jnp.where(slot_oh, plog_t[:, None, :], 0), axis=-1)
    ent_v = jnp.sum(jnp.where(slot_oh, plog_v[:, None, :], 0), axis=-1)
    old_t = jnp.sum(jnp.where(slot_oh, log_term[:, None, :], 0), axis=-1)
    conf_e = in_batch & (abs_e <= log_len[:, None]) & (old_t != ent_t)
    conflict_any = jnp.any(conf_e, axis=1)
    # Disk truncation is synchronous (the state file shrinks in place) but
    # the rewritten suffix is an ASYNC append until the next fsync: the
    # durable watermark drops to just below the first conflicting index.
    # Overwrites at matching (index, term) are byte-identical (log
    # matching) and cost no durability. In correct mode the handler fsync
    # below restores durable_len = log_len in the same tick.
    first_conf = jnp.min(jnp.where(conf_e, abs_e, _BIG), axis=1)
    durable_len = jnp.where(
        conflict_any, jnp.minimum(durable_len, first_conf - 1), durable_len
    )
    hit = in_batch[..., None] & slot_oh               # [n, e, cap]
    any_hit = jnp.any(hit, axis=1)
    log_term = jnp.where(
        any_hit, jnp.sum(jnp.where(hit, ent_t[..., None], 0), axis=1), log_term
    )
    log_val = jnp.where(
        any_hit, jnp.sum(jnp.where(hit, ent_v[..., None], 0), axis=1), log_val
    )
    if cfg.metrics:
        # the submit stamp replicates WITH the entry (read-at-delivery from
        # the sender's live stamp ring, same one-hot as the payload), so any
        # copy of an injected command carries its original leader-append
        # tick — what the commit-latency fold below reads
        plog_s = jnp.sum(
            jnp.where(pick[:, :, None], log_tick[None, :, :], 0), axis=1
        )
        ent_s = jnp.sum(jnp.where(slot_oh, plog_s[:, None, :], 0), axis=-1)
        log_tick = jnp.where(
            any_hit, jnp.sum(jnp.where(hit, ent_s[..., None], 0), axis=1),
            log_tick,
        )
    batch_end = jnp.minimum(prev + nent, base + cap)  # ring overflow: drop tail
    # Conflict => truncate to the rewritten batch; otherwise never shrink
    # (a heartbeat must not drop entries a newer AE already appended).
    # (under bug == "no_truncate", conflict_any is vacuously False: in_batch
    # was restricted to abs_e > log_len above, so the conflict conjunction
    # (abs_e <= log_len) can never hold — the buggy log only ever grows)
    log_len = jnp.where(
        success,
        jnp.where(conflict_any, batch_end, jnp.maximum(log_len, batch_end)),
        log_len,
    )
    commit = jnp.where(
        success,
        jnp.maximum(
            commit, jnp.minimum(picked(pick, s.ae_req_commit), batch_end)
        ),
        commit,
    )
    # Failure hint for fast backtracking (term-skip): first index of the
    # conflicting term, or our log length if the leader's prev is past our end.
    over = prev > log_len
    conf_term = _term_at(log_term, snap_term, base, prev, cap)
    cand = (abs_arr <= log_len[:, None]) & (log_term == conf_term[:, None])
    first_abs = jnp.min(jnp.where(cand, abs_arr, _BIG), axis=1)
    has_cand = jnp.any(cand, axis=1)
    hint = jnp.where(
        over, log_len,
        jnp.maximum(jnp.where(has_cand, first_abs - 1, base), base),
    )
    rsp_match = jnp.where(success, batch_end, hint)
    if cfg.bug != "ack_before_fsync":
        # persist-before-reply: the ack (rsp_match) exposes the appended
        # suffix — fsync before it leaves. Under the planted bug a
        # follower acks from volatile state; the leader commit-counts the
        # ack, the follower crashes inside the fsync window, and the
        # "committed" entry evaporates from the only majority that had it
        # (the commit-shadow / prefix-hash durability oracles must fire).
        durable_len = jnp.where(got, log_len, durable_len)
        durable_term = jnp.where(got, term, durable_term)
        durable_voted_for = jnp.where(got, voted_for, durable_voted_for)
    delay, lost = _net_draws(kn, blk, (n,))
    delay = delay * limp  # gray limp: the FOLLOWER is the sender
    send = got & ~lost  # per follower (one response per tick)
    # KEEP-OLDEST for periodically-regenerated messages: an occupied slot
    # (an in-flight response, incl. deferred ones) keeps its message and the
    # new send is dropped. With overwrite-newest, any delay span with
    # delay_min >= 2 starves the channel permanently — each tick's fresh
    # response re-stamps the slot into the future before its due tick ever
    # arrives. Dropping the new send is ordinary message loss, which every
    # consumer already tolerates; the channel then delivers one message per
    # round trip. (RV responses stay newest-wins: vote requests are one-shot
    # per election timeout, so they cannot starve, and a fresher term is the
    # more adversarial payload to deliver.)
    resp = pick.T & send[None, :] & (ae_rsp_t == 0)  # slot [leader, follower]
    ae_rsp_t = jnp.where(resp, (t + delay)[None, :], ae_rsp_t)
    ae_rsp_term = jnp.where(resp, term[None, :], ae_rsp_term)
    ae_rsp_success = jnp.where(resp, success[None, :], ae_rsp_success)
    ae_rsp_match = jnp.where(resp, rsp_match[None, :], ae_rsp_match)

    # Candidate -> leader on majority (election win; raft.rs:286-292 drain path).
    win = alive & (role == CANDIDATE) & (jnp.sum(votes, axis=1) >= kn.majority)
    role = jnp.where(win, LEADER, role)
    next_idx = jnp.where(win[:, None], log_len[:, None] + 1, next_idx)
    match_idx = jnp.where(win[:, None], 0, match_idx)
    hb = jnp.where(win, 0, hb)  # announce leadership with an immediate heartbeat
    # A fresh leader appends a current-term NO-OP, exempt from flow control:
    # the current-term commit rule can never advance over a backlog of
    # old-term entries, and the flow gate (config.py uncommitted_cap) blocks
    # service proposals at exactly that moment — the no-op is the bounded,
    # always-roomy (len - base <= flow_cap + compact_every < cap) entry that
    # restarts commit progress. The classic Raft §8 leader no-op.
    nop = win & (log_len - base < cap)
    nop_hit = nop[:, None] & (
        jnp.arange(cap, dtype=I32)[None, :] == _slot(log_len + 1, cap)[:, None]
    )
    log_term = jnp.where(nop_hit, term[:, None], log_term)
    log_val = jnp.where(nop_hit, NOOP_CMD, log_val)
    if cfg.metrics:
        # a no-op is not a client op: stamp 0 so the latency fold skips it
        # (and so a stale stamp from an overwritten entry cannot leak in)
        log_tick = jnp.where(nop_hit, 0, log_tick)
    log_len = jnp.where(nop, log_len + 1, log_len)
    # leader appends persist at append (start() -> persist()): the eye row
    # of the commit count below reads log_len, so it must be durable. The
    # winner's term/voted_for were fsynced at candidacy and are unchanged.
    durable_len = jnp.where(nop, log_len, durable_len)

    # ------------------------------------------------- timers: election timeout
    running = alive & (role != LEADER)
    timer = jnp.where(running, timer - 1, timer)
    fired = running & (timer <= 0)
    term = jnp.where(fired, term + 1, term)
    role = jnp.where(fired, CANDIDATE, role)
    voted_for = jnp.where(fired, me, voted_for)
    votes = jnp.where(fired[:, None], eye, votes)
    timer = jnp.where(fired, _timeout_draw(kn, blk, (n,), skew), timer)
    # start_election persists before any RequestVote leaves (raft.rs:248).
    # Kept under ack_before_fsync: the bug strips only the HANDLER replies.
    durable_term = jnp.where(fired, term, durable_term)
    durable_voted_for = jnp.where(fired, voted_for, durable_voted_for)
    durable_len = jnp.where(fired, log_len, durable_len)

    llt = jnp.where(
        log_len > base, _row_gather(log_term, _slot(log_len, cap), cap), snap_term
    )
    delay, lost = _net_draws(kn, blk, (n, n))
    delay = delay * limp[None, :]  # gray limp: src is the column axis
    send_rv = fired[None, :] & ~eye & adj & ~lost  # [dst, src]; adj[dst, src]
    #                                               = link src->dst usable
    rv_req_t = jnp.where(send_rv, t + delay, rv_req_t)
    rv_req_term = jnp.where(send_rv, term[None, :], s.rv_req_term)
    rv_req_lli = jnp.where(send_rv, log_len[None, :], s.rv_req_lli)
    rv_req_llt = jnp.where(send_rv, llt[None, :], s.rv_req_llt)

    # --------------------------------------- client command injection at leaders
    lead = alive & (role == LEADER)
    inject = (
        lead & blk.bern(kn.p_client_cmd, (n,))
        & (log_len - base < cap)
        & (log_len - commit < kn.flow_cap)  # proposal backpressure (config.py)
    )
    cmd_val = s.next_cmd * n + me + 1  # unique within the cluster, never 0
    inj_hit = inject[:, None] & (lane == _slot(log_len + 1, cap)[:, None])
    log_term = jnp.where(inj_hit, term[:, None], log_term)
    log_val = jnp.where(inj_hit, cmd_val[:, None], log_val)
    if cfg.metrics:
        # the submit stamp: the tick this client command entered the system
        log_tick = jnp.where(inj_hit, t, log_tick)
    log_len = jnp.where(inject, log_len + 1, log_len)
    durable_len = jnp.where(inject, log_len, durable_len)  # start()->persist
    next_cmd = s.next_cmd + jnp.any(inject).astype(I32)

    # -------------------------------------------- leader heartbeat / replication
    hb = jnp.where(lead, hb - 1, hb)
    fire_hb = lead & (hb <= 0)
    hb = jnp.where(fire_hb, kn.heartbeat_ticks, hb)
    # A peer behind the leader's snapshot boundary gets an install-snapshot
    # trigger instead of entries (raft.rs:159 InstallSnapshot).
    need_snap = next_idx.T <= base[None, :]  # [dst, src]
    prev_m = next_idx.T - 1  # [dst, src]: src's prev index for dst
    n_m = jnp.clip(log_len[None, :] - prev_m, 0, ae_max)
    # Entry payloads are NOT gathered here — the delivery phase reads them
    # from the sender's live log (read-at-delivery; see the AE delivery
    # comment). Only prev's term is resolved at send (the log-matching probe).
    oh_p = jnp.arange(cap, dtype=I32) == _slot(prev_m, cap)[..., None]
    prev_term_m = jnp.where(
        prev_m > base[None, :],
        jnp.sum(jnp.where(oh_p, log_term[None, :, :], 0), axis=-1),
        snap_term[None, :],
    )
    delay, lost = _net_draws(kn, blk, (n, n))
    delay = delay * limp[None, :]  # gray limp: src is the column axis
    # Eager replication: a leader with unsent entries for a peer fires an AE
    # at once — the reference replicates on start() immediately
    # (raft.rs:266-293 fan-out); the heartbeat cadence governs only the idle
    # case (and so the idle RPC budget, count_2b). Without this, replication
    # throughput caps at ae_max/heartbeat_ticks and a hot leader's window
    # outruns its followers.
    pending = lead[None, :] & (next_idx.T <= log_len[None, :])  # [dst, src]
    # keep-oldest (see the AE-response comment): eager per-tick resends must
    # not clobber an in-flight request or delay_min >= 2 starves the channel
    send_ae = (
        (fire_hb[None, :] | pending) & ~eye & adj & ~lost & ~need_snap
        & (ae_req_t == 0)
    )
    ae_req_t = jnp.where(send_ae, t + delay, ae_req_t)
    ae_req_term = jnp.where(send_ae, term[None, :], s.ae_req_term)
    ae_req_prev = jnp.where(send_ae, prev_m, s.ae_req_prev)
    ae_req_prev_term = jnp.where(send_ae, prev_term_m, s.ae_req_prev_term)
    ae_req_n = jnp.where(send_ae, n_m, s.ae_req_n)
    ae_req_commit = jnp.where(send_ae, commit[None, :], s.ae_req_commit)
    delay_sn, lost_sn = _net_draws(kn, blk, (n, n))
    delay_sn = delay_sn * limp[None, :]  # gray limp: src is the column axis
    send_sn = (
        fire_hb[None, :] & ~eye & adj & ~lost_sn & need_snap & (sn_req_t == 0)
    )
    sn_req_t = jnp.where(send_sn, t + delay_sn, sn_req_t)
    sn_req_term = jnp.where(send_sn, term[None, :], s.sn_req_term)
    # advance next_idx past the snapshot on send (retried via hints if lost)
    next_idx = jnp.where(send_sn.T, base[:, None] + 1, next_idx)

    # ------------------------------------------------------------ commit advance
    mi = jnp.where(eye, log_len[:, None], match_idx)
    # majority-th largest match; the quorum size is a dynamic knob, so the
    # column pick is a (uniform-index) take_along_axis, not a static slice
    kth = jnp.take_along_axis(
        -jnp.sort(-mi, axis=1),
        jnp.broadcast_to(jnp.clip(kn.majority - 1, 0, n - 1), (n, 1)),
        axis=1,
    )[:, 0]
    cur_term_ok = (kth > base) & (
        _term_at(log_term, snap_term, base, kth, cap) == term
    )
    if cfg.bug == "commit_any_term":
        # planted bug: drop the §5.4.2 current-term commit rule — the exact
        # Figure-8 mistake (commit by counting replicas of an old-term
        # entry); the commit-shadow oracle must fire. config.py RAFT_BUGS.
        cur_term_ok = kth > base
    commit = jnp.where(lead & cur_term_ok, jnp.maximum(commit, kth), commit)

    # ------------------------------------------------------------------- oracle
    viol = jnp.asarray(0, I32)
    # Election safety: two live leaders sharing a term (tester.rs:81-83).
    is_lead = alive & (role == LEADER)
    dual = (
        is_lead[:, None] & is_lead[None, :] & ~eye & (term[:, None] == term[None, :])
    )
    viol |= jnp.where(jnp.any(dual), VIOLATION_DUAL_LEADER, 0)
    # Log matching: same (index, term) => identical prefix, over the ring
    # overlap of each pair (entries below either base are committed and are
    # covered by the shadow oracle). The canonical layout makes this pure
    # elementwise: lane k of every node holds the same index residue, so two
    # nodes share lane k's index iff their windows overlap there. The prefix
    # property "a term match at a2 implies equality at every shared a1 <= a2"
    # is checked as min(bad indices) <= max(term-matched indices) — a bad pair
    # AT the matched index is caught because min <= max is inclusive.
    live = abs_arr <= log_len[:, None]  # (abs_arr > base holds by construction)
    overlap = (
        (abs_arr[:, None, :] == abs_arr[None, :, :])
        & live[:, None, :] & live[None, :, :]
    )
    t_eq = log_term[:, None, :] == log_term[None, :, :]
    v_eq = log_val[:, None, :] == log_val[None, :, :]
    tmatch = overlap & t_eq
    bad = overlap & ~(t_eq & v_eq)
    min_bad = jnp.min(jnp.where(bad, abs_arr[:, None, :], _BIG), axis=2)
    max_tm = jnp.max(jnp.where(tmatch, abs_arr[:, None, :], 0), axis=2)
    viol |= jnp.where(jnp.any(min_bad <= max_tm), VIOLATION_LOG_MATCHING, 0)
    # Commit durability: every entry any node ever committed is recorded in a
    # canonical-ring shadow log; later commits must agree (catches
    # Figure-8-style commit loss; the online analogue of push_and_check,
    # tester.rs:379-397). Sliding the shadow window is a pure base bump: stale
    # lanes are never read (their nominal index exceeds shadow_len) and are
    # overwritten when commits reach their lane's new index.
    shadow_term, shadow_val = s.shadow_term, s.shadow_val
    shadow_len = s.shadow_len
    need = jnp.max(jnp.where(alive, commit, 0))
    shadow_base = jnp.maximum(s.shadow_base, need - cap)
    # fold entries sliding out of the shadow window into its prefix hash
    # (indices (old base, new base]; new base never outruns the recorded
    # length because a per-tick commit jump is bounded by the log window)
    old_abs = _lane_abs(s.shadow_base, cap)
    slide = old_abs <= jnp.minimum(shadow_base, s.shadow_len)
    shadow_prefix_hash = s.shadow_prefix_hash ^ jnp.bitwise_xor.reduce(
        jnp.where(slide, _entry_mix(s.shadow_term, s.shadow_val, old_abs), 0)
    )
    sh_abs = _lane_abs(shadow_base, cap)  # [cap]
    # metrics: this tick's shadow-record stamps (a per-tick SCRATCH, reset
    # every tick — state.py shadow_sub). A lane goes nonzero exactly when a
    # stamped client entry is recorded below, so "stamp > 0" is both the
    # device fold mask and the flight recorder's exact host-recompute mask.
    shadow_sub = (jnp.zeros((cap,), I32) if cfg.metrics else s.shadow_sub)
    for i in range(n):
        c = commit[i]
        agree = sh_abs == abs_arr[i]  # lane holds the same index in both rings
        known = agree & (sh_abs <= jnp.minimum(c, shadow_len))
        differ = known & ((shadow_term != log_term[i]) | (shadow_val != log_val[i]))
        viol |= jnp.where(jnp.any(differ), VIOLATION_COMMIT_SHADOW, 0)
        new = agree & (sh_abs > shadow_len) & (sh_abs <= c)
        shadow_term = jnp.where(new, log_term[i], shadow_term)
        shadow_val = jnp.where(new, log_val[i], shadow_val)
        if cfg.metrics:
            shadow_sub = jnp.where(new, log_tick[i], shadow_sub)
        shadow_len = jnp.maximum(shadow_len, c)
    # Commit-latency fold (ISSUE 10): an injected command's ack is its
    # commit — the tick the durability shadow first records it. Latency =
    # record tick - submit stamp; no-ops and service-layer entries carry
    # stamp 0 and are skipped (service layers fold their own clerk-ack
    # latencies instead, kv.py/shardkv.py).
    lat_hist = s.lat_hist
    phase_hist, phase_ticks, lat_ticks = s.phase_hist, s.phase_ticks, s.lat_ticks
    worst = (s.worst_lat, s.worst_phases, s.worst_key, s.worst_client,
             s.worst_sub)
    if cfg.metrics:
        lats = t - shadow_sub
        rec_mask = shadow_sub > 0
        lat_hist = fold_latencies(lat_hist, lats, rec_mask)
        # attribution (ISSUE 12): a raft-injected command is born AT a
        # leader (leader_wait 0) and its commit is its ack (apply/ack 0),
        # so its whole latency is the replicate phase — the exact-sum
        # decomposition degenerates to one leg on this layer. Folding all
        # four rows keeps the mass invariant (each row's total == acked).
        zeros = jnp.zeros_like(lats)
        phases = jnp.stack([
            lats if name == "replicate" else zeros
            for name in LATENCY_PHASES
        ])
        phase_hist, phase_ticks, lat_ticks = fold_phases(
            phase_hist, phase_ticks, lat_ticks, phases, lats, rec_mask
        )
        worst = update_worst(
            worst, lats, rec_mask, phases,
            jnp.full_like(lats, -1), jnp.full_like(lats, -1), shadow_sub,
        )

    # Prefix durability (the long-range extension of the shadow oracle, which
    # only sees the last `cap` committed entries; the round-1 advisory gap):
    # equal snapshot boundaries must mean equal compacted prefixes — across
    # nodes, and against the shadow's own slid-out fold.
    same_base = (
        (base[:, None] == base[None, :]) & (base[:, None] > 0) & ~eye
        & alive[:, None] & alive[None, :]
    )
    viol |= jnp.where(
        jnp.any(same_base & (prefix_hash[:, None] != prefix_hash[None, :])),
        VIOLATION_PREFIX_DIVERGE, 0,
    )
    vs_shadow = (
        alive & (base == s.shadow_base) & (base > 0)
        & (prefix_hash != s.shadow_prefix_hash)
    )
    viol |= jnp.where(jnp.any(vs_shadow), VIOLATION_PREFIX_DIVERGE, 0)

    violations = s.violations | viol
    first_violation_tick = jnp.where(
        (s.first_violation_tick < 0) & (viol != 0), t, s.first_violation_tick
    )
    first_leader_tick = jnp.where(
        (s.first_leader_tick < 0) & jnp.any(is_lead), t, s.first_leader_tick
    )

    # -------------------------------------------------------------- compaction
    # AFTER the oracle on purpose: the shadow must record entries committed
    # this tick before the boundary passes them. Snapshot through the boundary
    # (commit, or the service layer's apply cursor) once compact_every entries
    # accumulated past base. With the canonical ring this is a pure index
    # bump — no data movement. Service layers observe base advancing and
    # capture their own state (kv.py).
    boundary = jnp.where(
        kn.compact_at_commit, commit, jnp.minimum(compact_floor, commit)
    )
    do_compact = alive & (boundary - base >= kn.compact_every)
    new_snap_term = _term_at(log_term, snap_term, base, boundary, cap)
    # fold the entries crossing the boundary into the node's prefix hash
    out_lanes = do_compact[:, None] & (abs_arr <= boundary[:, None])
    prefix_hash = prefix_hash ^ jnp.bitwise_xor.reduce(
        jnp.where(out_lanes, _entry_mix(log_term, log_val, abs_arr), 0), axis=1
    )
    snap_term = jnp.where(do_compact, new_snap_term, snap_term)
    base = jnp.where(do_compact, boundary, base)
    # Writing the snapshot file is itself a durable write (snapshot() ->
    # persist()): everything through the new boundary is on disk, which
    # keeps base <= durable_len even when a bug let commit outrun the
    # watermark. The suffix past the boundary stays volatile.
    durable_len = jnp.where(
        do_compact, jnp.maximum(durable_len, boundary), durable_len
    )

    # ------------------------------------------------------- background fsync
    # Per-node staggered cadence (stagger avoids a lockstep all-nodes-sync
    # artifact): node i syncs its full persistent state every fsync_every
    # ticks. fsync_every=1 -> durable == live at every tick end, i.e. the
    # historic perfect-persistence model (and the default). The traced-int
    # modulo is one [n] op per tick — noise next to the [n, cap] phases
    # (the _DrawBlock modulo cliff was per-draw at [n, n] scale).
    # Gray fsync stalls (ISSUE 19): a write spike delays the BACKGROUND
    # cadence for a drawn duration — the durable watermark lags, widening
    # the ack_before_fsync volatile window. The explicit persist-before-*
    # syncs above are NOT stalled (they model blocking fsync calls that
    # complete within the tick), so the correct algorithm stays oracle-
    # safe under any stall schedule. Onset rides the free low byte of the
    # snap-accept timer words, the duration that of the grant-timer words
    # (zero extra PRNG budget); a restart clears the stall with the rest
    # of the process state.
    stall_on = (
        alive & (kn.fsync_stall_ticks >= 1)
        & _bern8(w_snap_tmr, kn.p_fsync_stall)
    )
    fsync_stall = jnp.where(
        restart, 0,
        jnp.where(
            stall_on, _randint8(w_grant_tmr, 1, kn.fsync_stall_ticks),
            jnp.maximum(s.fsync_stall - 1, 0),
        ),
    )
    do_fsync = alive & ((t + me) % kn.fsync_every == 0) & (fsync_stall == 0)
    durable_len = jnp.where(do_fsync, log_len, durable_len)
    durable_term = jnp.where(do_fsync, term, durable_term)
    durable_voted_for = jnp.where(do_fsync, voted_for, durable_voted_for)

    # ------------------------------------------------ metrics: event counters
    # One increment per node per event per tick (config.METRIC_EVENTS order;
    # the per-type delivery counts are the same exact quantities the trace
    # module derives, so their sum equals the msg_count delta — test-pinned).
    ev_counts = s.ev_counts
    if cfg.metrics:
        ev_counts = ev_counts + jnp.stack([
            jnp.sum(win, dtype=I32),                  # elections_won
            jnp.sum(term > term0, dtype=I32),         # term_bumps
            jnp.sum(crash, dtype=I32),                # crashes
            jnp.sum(restart, dtype=I32),              # restarts
            d_rv_req, d_rv_rsp, d_ae_req, d_ae_rsp, d_sn,
            jnp.sum(commit > commit0, dtype=I32),     # commit_advances
        ])

    return ClusterState(
        tick=t,
        term=term, voted_for=voted_for, role=role, timer=timer, hb=hb, alive=alive,
        limp=limp, fsync_stall=fsync_stall,
        log_term=log_term, log_val=log_val, log_len=log_len,
        base=base, snap_term=snap_term, prefix_hash=prefix_hash,
        commit=commit, compact_floor=compact_floor,
        durable_len=durable_len, durable_term=durable_term,
        durable_voted_for=durable_voted_for,
        votes=votes, next_idx=next_idx, match_idx=match_idx, adj=adj,
        rv_req_t=rv_req_t, rv_req_term=rv_req_term,
        rv_req_lli=rv_req_lli, rv_req_llt=rv_req_llt,
        rv_rsp_t=rv_rsp_t, rv_rsp_term=rv_rsp_term, rv_rsp_granted=rv_rsp_granted,
        ae_req_t=ae_req_t, ae_req_term=ae_req_term, ae_req_prev=ae_req_prev,
        ae_req_prev_term=ae_req_prev_term, ae_req_n=ae_req_n,
        ae_req_commit=ae_req_commit,
        ae_rsp_t=ae_rsp_t, ae_rsp_term=ae_rsp_term,
        ae_rsp_success=ae_rsp_success, ae_rsp_match=ae_rsp_match,
        sn_req_t=sn_req_t,
        sn_req_term=sn_req_term,
        snap_installed_src=snap_installed_src,
        snap_installed_len=snap_installed_len,
        next_cmd=next_cmd,
        shadow_term=shadow_term, shadow_val=shadow_val,
        shadow_base=shadow_base, shadow_len=shadow_len,
        shadow_prefix_hash=shadow_prefix_hash,
        violations=violations, first_violation_tick=first_violation_tick,
        first_leader_tick=first_leader_tick,
        msg_count=s.msg_count + delivered,
        snap_install_count=snap_install_count,
        log_tick=log_tick,
        shadow_sub=shadow_sub,
        lat_hist=lat_hist,
        ev_counts=ev_counts,
        phase_hist=phase_hist,
        phase_ticks=phase_ticks,
        lat_ticks=lat_ticks,
        worst_lat=worst[0],
        worst_phases=worst[1],
        worst_key=worst[2],
        worst_client=worst[3],
        worst_sub=worst[4],
    )
