"""One lockstep tick of a simulated Raft cluster, as a pure JAX function.

This is the batched re-imagination of the reference's per-node async tick
(/root/reference/src/raft/raft.rs: election timer 260-263, RequestVote fan-out
266-293, RPC handlers 213-233) plus the simulator semantics it runs on
(SURVEY.md §2.6): per-message loss/latency draws, pairwise partitions, kill/restart
with persistent state, message counting.

Phase order within a tick (this ordering gives persist-before-send for free — all
sends are computed from post-update persistent arrays, mirroring the reference's
"persist after RPC handlers mutate state" rule at raft.rs:224-233):

  1. faults     — crash / restart / repartition draws
  2. deliver    — process every mailbox slot due this tick (sequential over sources
                  for per-node sequential semantics; vectorized over destinations)
  3. timers     — election timeouts -> candidacy + RequestVote broadcast;
                  client command injection at leaders; leader heartbeat ->
                  AppendEntries broadcast with entries from next_idx
  4. commit     — leader advances commit via majority-match (current-term rule)
  5. oracle     — safety invariant reductions (election safety, log matching,
                  commit durability) + liveness/stat bookkeeping

Control flow divergence across the batch is handled with masked updates
(`jnp.where`) throughout; loops are only over the (static, tiny) node and
entry-batch axes, so XLA sees fully static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from madraft_tpu.tpusim.config import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    SimConfig,
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)
from madraft_tpu.tpusim.state import ClusterState, I32

# PRNG site ids (fold_in constants) — one stream per independent decision site.
_S_FAULT, _S_RVREQ, _S_AEREQ, _S_TIMER, _S_CLIENT, _S_HB, _S_GRANT, _S_AERESET = (
    0, 1, 2, 3, 4, 5, 6, 7,
)


def _timeout_draw(cfg: SimConfig, key: jax.Array, shape) -> jax.Array:
    return jax.random.randint(
        key, shape, cfg.election_timeout_min, cfg.election_timeout_max + 1, dtype=I32
    )


def _net_draws(cfg: SimConfig, key: jax.Array, shape):
    """(delay, lost) draws for a batch of sends."""
    kd, kl = jax.random.split(key)
    delay = jax.random.randint(kd, shape, cfg.delay_min, cfg.delay_max + 1, dtype=I32)
    lost = jax.random.bernoulli(kl, cfg.loss_prob, shape)
    return delay, lost


def _row_term(log_term: jax.Array, pos: jax.Array, cap: int) -> jax.Array:
    """log_term[i, pos[i]] with clipped gather; callers mask invalid positions."""
    n = log_term.shape[0]
    return log_term[jnp.arange(n), jnp.clip(pos, 0, cap - 1)]


def step_cluster(cfg: SimConfig, s: ClusterState, cluster_key: jax.Array) -> ClusterState:
    n, cap, ae_max = cfg.n_nodes, cfg.log_cap, cfg.ae_max
    t = s.tick + 1  # messages sent at tick t-1 with delay 1 arrive now
    key = jax.random.fold_in(cluster_key, t)
    me = jnp.arange(n, dtype=I32)
    eye = jnp.eye(n, dtype=jnp.bool_)

    # ------------------------------------------------------------------ faults
    kf = jax.random.split(jax.random.fold_in(key, _S_FAULT), 5)
    restart = (~s.alive) & jax.random.bernoulli(kf[0], cfg.p_restart, (n,))
    crash_draw = s.alive & jax.random.bernoulli(kf[1], cfg.p_crash, (n,))
    # Keep a quorum-capable cluster: at most max_dead simultaneously-dead nodes.
    dead_after_restart = jnp.sum((~s.alive) & (~restart))
    budget = jnp.asarray(cfg.max_dead, I32) - dead_after_restart
    crash = crash_draw & (jnp.cumsum(crash_draw.astype(I32)) <= budget)
    alive = (s.alive | restart) & ~crash

    # Restart = recovery from persisted state (term/voted_for/log survive; the
    # volatile set resets — raft.rs:194-211 restore(), tester.rs:284-327).
    role = jnp.where(restart, FOLLOWER, s.role)
    timer = jnp.where(restart, _timeout_draw(cfg, kf[2], (n,)), s.timer)
    hb = jnp.where(restart, 0, s.hb)
    commit = jnp.where(restart, 0, s.commit)
    votes = jnp.where(restart[:, None], False, s.votes)
    next_idx = jnp.where(restart[:, None], 1, s.next_idx)
    match_idx = jnp.where(restart[:, None], 0, s.match_idx)

    # Partition schedule: random 2-coloring / heal (connect2/disconnect2 masks,
    # /root/reference/src/kvraft/tester.rs:88-124).
    u_part = jax.random.uniform(kf[3], ())
    colors = jax.random.bernoulli(kf[4], 0.5, (n,))
    part_adj = colors[:, None] == colors[None, :]
    do_part = u_part < cfg.p_repartition
    do_heal = (~do_part) & (u_part < cfg.p_repartition + cfg.p_heal)
    adj = jnp.where(do_part, part_adj, jnp.where(do_heal, True, s.adj)) | eye

    term, voted_for = s.term, s.voted_for
    log_term, log_val, log_len = s.log_term, s.log_val, s.log_len
    rv_rsp_t, rv_rsp_term, rv_rsp_granted = s.rv_rsp_t, s.rv_rsp_term, s.rv_rsp_granted
    ae_rsp_t, ae_rsp_term = s.ae_rsp_t, s.ae_rsp_term
    ae_rsp_success, ae_rsp_match = s.ae_rsp_success, s.ae_rsp_match
    delivered = jnp.asarray(0, I32)

    # ----------------------------------------------------- deliver: RV requests
    k_grant = jax.random.fold_in(key, _S_GRANT)
    for src in range(n):
        arr = (s.rv_req_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = s.rv_req_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        my_llt = jnp.where(log_len > 0, _row_term(log_term, log_len - 1, cap), 0)
        log_ok = (s.rv_req_llt[:, src] > my_llt) | (
            (s.rv_req_llt[:, src] == my_llt) & (s.rv_req_lli[:, src] >= log_len)
        )
        grant = arr & (mterm == term) & ((voted_for == -1) | (voted_for == src)) & log_ok
        voted_for = jnp.where(grant, src, voted_for)
        ks = jax.random.fold_in(k_grant, src)
        timer = jnp.where(grant, _timeout_draw(cfg, ks, (n,)), timer)
        delay, lost = _net_draws(cfg, jax.random.fold_in(jax.random.fold_in(key, _S_RVREQ), src), (n,))
        send = arr & adj[:, src] & ~lost
        rv_rsp_t = rv_rsp_t.at[src, :].set(jnp.where(send, t + delay, rv_rsp_t[src, :]))
        rv_rsp_term = rv_rsp_term.at[src, :].set(jnp.where(send, term, rv_rsp_term[src, :]))
        rv_rsp_granted = rv_rsp_granted.at[src, :].set(
            jnp.where(send, grant, rv_rsp_granted[src, :])
        )
    rv_req_t = jnp.where(s.rv_req_t == t, 0, s.rv_req_t)

    # ----------------------------------------------------- deliver: AE requests
    k_aereset = jax.random.fold_in(key, _S_AERESET)
    for src in range(n):
        arr = (s.ae_req_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = s.ae_req_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        acc = arr & (mterm == term)  # AppendEntries from the current-term leader
        role = jnp.where(acc & (role == CANDIDATE), FOLLOWER, role)
        timer = jnp.where(
            acc, _timeout_draw(cfg, jax.random.fold_in(k_aereset, src), (n,)), timer
        )
        prev = s.ae_req_prev[:, src]
        prev_ok = (prev == 0) | (
            (prev <= log_len) & (_row_term(log_term, prev - 1, cap) == s.ae_req_prev_term[:, src])
        )
        success = acc & prev_ok
        nent = s.ae_req_n[:, src]
        conflict_any = jnp.zeros((n,), jnp.bool_)
        for e in range(ae_max):
            idx = prev + e  # 0-based slot of this batch entry
            in_batch = success & (e < nent) & (idx < cap)
            ent_t = s.ae_req_ent_term[:, src, e]
            ent_v = s.ae_req_ent_val[:, src, e]
            conflict_any |= in_batch & (idx < log_len) & (_row_term(log_term, idx, cap) != ent_t)
            slot = jnp.clip(idx, 0, cap - 1)
            log_term = log_term.at[me, slot].set(
                jnp.where(in_batch, ent_t, log_term[me, slot])
            )
            log_val = log_val.at[me, slot].set(
                jnp.where(in_batch, ent_v, log_val[me, slot])
            )
        batch_end = jnp.clip(prev + nent, 0, cap)
        # Conflict => truncate to the rewritten batch; otherwise never shrink
        # (a heartbeat must not drop entries a newer AE already appended).
        log_len = jnp.where(
            success,
            jnp.where(conflict_any, batch_end, jnp.maximum(log_len, batch_end)),
            log_len,
        )
        commit = jnp.where(
            success,
            jnp.maximum(commit, jnp.minimum(s.ae_req_commit[:, src], prev + nent)),
            commit,
        )
        # Failure hint for fast backtracking (term-skip): first index of the
        # conflicting term, or our log length if the leader's prev is past our end.
        over = prev > log_len
        conf_term = _row_term(log_term, prev - 1, cap)
        first_of_term = jnp.argmax(log_term == conf_term[:, None], axis=1).astype(I32)
        hint = jnp.where(over, log_len, first_of_term)
        rsp_match = jnp.where(success, prev + nent, hint)
        delay, lost = _net_draws(cfg, jax.random.fold_in(jax.random.fold_in(key, _S_AEREQ), src), (n,))
        send = arr & adj[:, src] & ~lost
        ae_rsp_t = ae_rsp_t.at[src, :].set(jnp.where(send, t + delay, ae_rsp_t[src, :]))
        ae_rsp_term = ae_rsp_term.at[src, :].set(jnp.where(send, term, ae_rsp_term[src, :]))
        ae_rsp_success = ae_rsp_success.at[src, :].set(
            jnp.where(send, success, ae_rsp_success[src, :])
        )
        ae_rsp_match = ae_rsp_match.at[src, :].set(
            jnp.where(send, rsp_match, ae_rsp_match[src, :])
        )
    ae_req_t = jnp.where(s.ae_req_t == t, 0, s.ae_req_t)

    # ---------------------------------------------------- deliver: RV responses
    for src in range(n):
        arr = (rv_rsp_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = rv_rsp_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        got = arr & rv_rsp_granted[:, src] & (role == CANDIDATE) & (mterm == term)
        votes = votes.at[:, src].set(votes[:, src] | got)
    rv_rsp_t = jnp.where(rv_rsp_t <= t, 0, rv_rsp_t)

    # ---------------------------------------------------- deliver: AE responses
    for src in range(n):
        arr = (ae_rsp_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = ae_rsp_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        ok = arr & (role == LEADER) & (mterm == term)
        succ = ok & ae_rsp_success[:, src]
        fail = ok & ~ae_rsp_success[:, src]
        m = ae_rsp_match[:, src]
        match_idx = match_idx.at[:, src].set(
            jnp.where(succ, jnp.maximum(match_idx[:, src], m), match_idx[:, src])
        )
        nxt = jnp.where(
            succ,
            jnp.maximum(next_idx[:, src], m + 1),
            jnp.where(fail, jnp.maximum(jnp.minimum(next_idx[:, src], m + 1), 1), next_idx[:, src]),
        )
        next_idx = next_idx.at[:, src].set(nxt)
    ae_rsp_t = jnp.where(ae_rsp_t <= t, 0, ae_rsp_t)

    # Candidate -> leader on majority (election win; raft.rs:286-292 drain path).
    win = alive & (role == CANDIDATE) & (jnp.sum(votes, axis=1) >= cfg.majority)
    role = jnp.where(win, LEADER, role)
    next_idx = jnp.where(win[:, None], log_len[:, None] + 1, next_idx)
    match_idx = jnp.where(win[:, None], 0, match_idx)
    hb = jnp.where(win, 0, hb)  # announce leadership with an immediate heartbeat

    # ------------------------------------------------- timers: election timeout
    kt = jax.random.split(jax.random.fold_in(key, _S_TIMER), 3)
    running = alive & (role != LEADER)
    timer = jnp.where(running, timer - 1, timer)
    fired = running & (timer <= 0)
    term = jnp.where(fired, term + 1, term)
    role = jnp.where(fired, CANDIDATE, role)
    voted_for = jnp.where(fired, me, voted_for)
    votes = jnp.where(fired[:, None], eye, votes)
    timer = jnp.where(fired, _timeout_draw(cfg, kt[0], (n,)), timer)

    llt = jnp.where(log_len > 0, _row_term(log_term, log_len - 1, cap), 0)
    delay, lost = _net_draws(cfg, kt[1], (n, n))
    send_rv = fired[None, :] & ~eye & adj.T & ~lost  # [dst, src], link src->dst
    rv_req_t = jnp.where(send_rv, t + delay, rv_req_t)
    rv_req_term = jnp.where(send_rv, term[None, :], s.rv_req_term)
    rv_req_lli = jnp.where(send_rv, log_len[None, :], s.rv_req_lli)
    rv_req_llt = jnp.where(send_rv, llt[None, :], s.rv_req_llt)

    # --------------------------------------- client command injection at leaders
    lead = alive & (role == LEADER)
    inject = (
        lead
        & jax.random.bernoulli(jax.random.fold_in(key, _S_CLIENT), cfg.p_client_cmd, (n,))
        & (log_len < cap)
    )
    slot = jnp.clip(log_len, 0, cap - 1)
    cmd_val = s.next_cmd * n + me + 1  # unique within the cluster, never 0
    log_term = log_term.at[me, slot].set(jnp.where(inject, term, log_term[me, slot]))
    log_val = log_val.at[me, slot].set(jnp.where(inject, cmd_val, log_val[me, slot]))
    log_len = jnp.where(inject, log_len + 1, log_len)
    next_cmd = s.next_cmd + jnp.any(inject).astype(I32)

    # -------------------------------------------- leader heartbeat / replication
    hb = jnp.where(lead, hb - 1, hb)
    fire_hb = lead & (hb <= 0)
    hb = jnp.where(fire_hb, cfg.heartbeat_ticks, hb)
    prev_m = next_idx.T - 1  # [dst, src]: src's prev index for dst
    n_m = jnp.clip(log_len[None, :] - prev_m, 0, ae_max)
    idxs = prev_m[:, :, None] + jnp.arange(ae_max, dtype=I32)[None, None, :]
    log_t_b = jnp.broadcast_to(log_term[None, :, :], (n, n, cap))
    log_v_b = jnp.broadcast_to(log_val[None, :, :], (n, n, cap))
    ent_t = jnp.take_along_axis(log_t_b, jnp.clip(idxs, 0, cap - 1), axis=2)
    ent_v = jnp.take_along_axis(log_v_b, jnp.clip(idxs, 0, cap - 1), axis=2)
    prev_term_m = jnp.where(
        prev_m > 0,
        jnp.take_along_axis(log_t_b, jnp.clip(prev_m - 1, 0, cap - 1)[:, :, None], axis=2)[:, :, 0],
        0,
    )
    delay, lost = _net_draws(cfg, jax.random.fold_in(key, _S_HB), (n, n))
    send_ae = fire_hb[None, :] & ~eye & adj.T & ~lost
    ae_req_t = jnp.where(send_ae, t + delay, ae_req_t)
    ae_req_term = jnp.where(send_ae, term[None, :], s.ae_req_term)
    ae_req_prev = jnp.where(send_ae, prev_m, s.ae_req_prev)
    ae_req_prev_term = jnp.where(send_ae, prev_term_m, s.ae_req_prev_term)
    ae_req_n = jnp.where(send_ae, n_m, s.ae_req_n)
    ae_req_commit = jnp.where(send_ae, commit[None, :], s.ae_req_commit)
    ae_req_ent_term = jnp.where(send_ae[:, :, None], ent_t, s.ae_req_ent_term)
    ae_req_ent_val = jnp.where(send_ae[:, :, None], ent_v, s.ae_req_ent_val)

    # ------------------------------------------------------------ commit advance
    mi = match_idx.at[me, me].set(log_len)
    kth = -jnp.sort(-mi, axis=1)[:, cfg.majority - 1]  # majority-th largest match
    cur_term_ok = (kth > 0) & (_row_term(log_term, kth - 1, cap) == term)
    commit = jnp.where(lead & cur_term_ok, jnp.maximum(commit, kth), commit)

    # ------------------------------------------------------------------- oracle
    viol = jnp.asarray(0, I32)
    # Election safety: two live leaders sharing a term (tester.rs:81-83).
    is_lead = alive & (role == LEADER)
    dual = (
        is_lead[:, None] & is_lead[None, :] & ~eye & (term[:, None] == term[None, :])
    )
    viol |= jnp.where(jnp.any(dual), VIOLATION_DUAL_LEADER, 0)
    # Log matching: same (index, term) => identical prefix (includes crashed nodes'
    # persisted logs — the property holds for all logs at all times).
    ks_ = jnp.arange(cap)
    both = ks_[None, None, :] < jnp.minimum(log_len[:, None], log_len[None, :])[:, :, None]
    tmatch = both & (log_term[:, None, :] == log_term[None, :, :])
    eq = tmatch & (log_val[:, None, :] == log_val[None, :, :])
    pref = jnp.cumprod((eq | ~both).astype(I32), axis=2).astype(jnp.bool_)
    viol |= jnp.where(jnp.any(tmatch & ~pref), VIOLATION_LOG_MATCHING, 0)
    # Commit durability: every entry any node ever committed is recorded in a
    # shadow log; later commits must agree (catches Figure-8-style commit loss;
    # the online analogue of StorageHandle.push_and_check, tester.rs:379-397).
    shadow_term, shadow_val, shadow_len = s.shadow_term, s.shadow_val, s.shadow_len
    for i in range(n):
        c = commit[i]
        known = ks_ < jnp.minimum(c, shadow_len)
        differ = known & (
            (shadow_term != log_term[i]) | (shadow_val != log_val[i])
        )
        viol |= jnp.where(jnp.any(differ), VIOLATION_COMMIT_SHADOW, 0)
        new = (ks_ >= shadow_len) & (ks_ < c)
        shadow_term = jnp.where(new, log_term[i], shadow_term)
        shadow_val = jnp.where(new, log_val[i], shadow_val)
        shadow_len = jnp.maximum(shadow_len, c)

    violations = s.violations | viol
    first_violation_tick = jnp.where(
        (s.first_violation_tick < 0) & (viol != 0), t, s.first_violation_tick
    )
    first_leader_tick = jnp.where(
        (s.first_leader_tick < 0) & jnp.any(is_lead), t, s.first_leader_tick
    )

    return ClusterState(
        tick=t,
        term=term, voted_for=voted_for, role=role, timer=timer, hb=hb, alive=alive,
        log_term=log_term, log_val=log_val, log_len=log_len, commit=commit,
        votes=votes, next_idx=next_idx, match_idx=match_idx, adj=adj,
        rv_req_t=rv_req_t, rv_req_term=rv_req_term,
        rv_req_lli=rv_req_lli, rv_req_llt=rv_req_llt,
        rv_rsp_t=rv_rsp_t, rv_rsp_term=rv_rsp_term, rv_rsp_granted=rv_rsp_granted,
        ae_req_t=ae_req_t, ae_req_term=ae_req_term, ae_req_prev=ae_req_prev,
        ae_req_prev_term=ae_req_prev_term, ae_req_n=ae_req_n,
        ae_req_commit=ae_req_commit,
        ae_req_ent_term=ae_req_ent_term, ae_req_ent_val=ae_req_ent_val,
        ae_rsp_t=ae_rsp_t, ae_rsp_term=ae_rsp_term,
        ae_rsp_success=ae_rsp_success, ae_rsp_match=ae_rsp_match,
        next_cmd=next_cmd,
        shadow_term=shadow_term, shadow_val=shadow_val, shadow_len=shadow_len,
        violations=violations, first_violation_tick=first_violation_tick,
        first_leader_tick=first_leader_tick,
        msg_count=s.msg_count + delivered,
    )
