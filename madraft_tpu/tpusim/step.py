"""One lockstep tick of a simulated Raft cluster, as a pure JAX function.

This is the batched re-imagination of the reference's per-node async tick
(/root/reference/src/raft/raft.rs: election timer 260-263, RequestVote fan-out
266-293, RPC handlers 213-233, snapshot path 149-168) plus the simulator
semantics it runs on (SURVEY.md §2.6): per-message loss/latency draws, pairwise
partitions, kill/restart with persistent state, message counting.

Phase order within a tick (this ordering gives persist-before-send for free — all
sends are computed from post-update persistent arrays, mirroring the reference's
"persist after RPC handlers mutate state" rule at raft.rs:224-233):

  1. faults     — crash / restart / repartition draws
  2. deliver    — process every mailbox slot due this tick (sequential over sources
                  for per-node sequential semantics; vectorized over destinations):
                  install-snapshot triggers first, then AE/RV requests/responses
  3. timers     — election timeouts -> candidacy + RequestVote broadcast;
                  client command injection at leaders; leader heartbeat ->
                  AppendEntries (or install-snapshot for peers behind the
                  leader's snapshot boundary) with entries from next_idx
  4. commit     — leader advances commit via majority-match (current-term rule)
  5. compact    — discard the window prefix up to the compaction boundary
                  (commit, or the service layer's apply cursor)
  6. oracle     — safety invariant reductions (election safety, log matching,
                  commit durability) + liveness/stat bookkeeping

The log is a WINDOW (see state.py): `base` is the snapshot boundary, slot k
holds absolute index base+k+1, `log_len`/`commit`/next/match indices are
absolute. Control-flow divergence across the batch is handled with masked
updates (`jnp.where`); loops are only over the (static, tiny) node and
entry-batch axes, so XLA sees fully static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from madraft_tpu.tpusim.config import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    SimConfig,
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)
from madraft_tpu.tpusim.state import ClusterState, I32

# PRNG site ids (fold_in constants) — one stream per independent decision site.
_S_FAULT, _S_RVREQ, _S_AEREQ, _S_TIMER, _S_CLIENT, _S_HB, _S_GRANT, _S_AERESET = (
    0, 1, 2, 3, 4, 5, 6, 7,
)
_S_SNREQ = 12
_S_SNRESET = 13


def _timeout_draw(cfg: SimConfig, key: jax.Array, shape) -> jax.Array:
    return jax.random.randint(
        key, shape, cfg.election_timeout_min, cfg.election_timeout_max + 1, dtype=I32
    )


def _net_draws(cfg: SimConfig, key: jax.Array, shape):
    """(delay, lost) draws for a batch of sends."""
    kd, kl = jax.random.split(key)
    delay = jax.random.randint(kd, shape, cfg.delay_min, cfg.delay_max + 1, dtype=I32)
    lost = jax.random.bernoulli(kl, cfg.loss_prob, shape)
    return delay, lost


def _row_gather(arr: jax.Array, pos: jax.Array, cap: int) -> jax.Array:
    """arr[i, pos[i]] with clipped gather; callers mask invalid positions."""
    n = arr.shape[0]
    return arr[jnp.arange(n), jnp.clip(pos, 0, cap - 1)]


def _term_at(log_term, snap_term, base, abs_idx, cap):
    """Term of absolute (1-based) index abs_idx per node; snap_term at the
    boundary itself. Callers mask positions outside (base, log_len]."""
    slot = abs_idx - base - 1
    return jnp.where(abs_idx <= base, snap_term, _row_gather(log_term, slot, cap))


def _shift_rows(arr: jax.Array, delta: jax.Array, cap: int) -> jax.Array:
    """Per-row left shift: out[i, k] = arr[i, k + delta[i]] (clipped gather)."""
    k = jnp.arange(cap, dtype=I32)[None, :]
    idx = jnp.clip(k + delta[:, None], 0, cap - 1)
    return jnp.take_along_axis(arr, idx, axis=1)


def step_cluster(cfg: SimConfig, s: ClusterState, cluster_key: jax.Array) -> ClusterState:
    n, cap, ae_max = cfg.n_nodes, cfg.log_cap, cfg.ae_max
    t = s.tick + 1  # messages sent at tick t-1 with delay 1 arrive now
    key = jax.random.fold_in(cluster_key, t)
    me = jnp.arange(n, dtype=I32)
    eye = jnp.eye(n, dtype=jnp.bool_)

    # ------------------------------------------------------------------ faults
    kf = jax.random.split(jax.random.fold_in(key, _S_FAULT), 5)
    restart = (~s.alive) & jax.random.bernoulli(kf[0], cfg.p_restart, (n,))
    crash_draw = s.alive & jax.random.bernoulli(kf[1], cfg.p_crash, (n,))
    # Keep a quorum-capable cluster: at most max_dead simultaneously-dead nodes.
    dead_after_restart = jnp.sum((~s.alive) & (~restart))
    budget = jnp.asarray(cfg.max_dead, I32) - dead_after_restart
    crash = crash_draw & (jnp.cumsum(crash_draw.astype(I32)) <= budget)
    alive = (s.alive | restart) & ~crash

    # Restart = recovery from persisted state (term/voted_for/log/base survive;
    # the volatile set resets — raft.rs:194-211 restore(), tester.rs:284-327).
    # The snapshot covers 1..base, so commit restarts at base, not 0.
    role = jnp.where(restart, FOLLOWER, s.role)
    timer = jnp.where(restart, _timeout_draw(cfg, kf[2], (n,)), s.timer)
    hb = jnp.where(restart, 0, s.hb)
    commit = jnp.where(restart, s.base, s.commit)
    compact_floor = jnp.where(restart, s.base, s.compact_floor)
    votes = jnp.where(restart[:, None], False, s.votes)
    next_idx = jnp.where(restart[:, None], 1, s.next_idx)
    match_idx = jnp.where(restart[:, None], 0, s.match_idx)

    # Partition schedule: random 2-coloring / heal (connect2/disconnect2 masks,
    # /root/reference/src/kvraft/tester.rs:88-124).
    u_part = jax.random.uniform(kf[3], ())
    colors = jax.random.bernoulli(kf[4], 0.5, (n,))
    part_adj = colors[:, None] == colors[None, :]
    do_part = u_part < cfg.p_repartition
    do_heal = (~do_part) & (u_part < cfg.p_repartition + cfg.p_heal)
    adj = jnp.where(do_part, part_adj, jnp.where(do_heal, True, s.adj)) | eye

    term, voted_for = s.term, s.voted_for
    log_term, log_val, log_len = s.log_term, s.log_val, s.log_len
    base, snap_term = s.base, s.snap_term
    rv_rsp_t, rv_rsp_term, rv_rsp_granted = s.rv_rsp_t, s.rv_rsp_term, s.rv_rsp_granted
    ae_rsp_t, ae_rsp_term = s.ae_rsp_t, s.ae_rsp_term
    ae_rsp_success, ae_rsp_match = s.ae_rsp_success, s.ae_rsp_match
    delivered = jnp.asarray(0, I32)
    snap_installed_src = jnp.full((n,), -1, I32)
    snap_installed_len = jnp.zeros((n,), I32)
    snap_install_count = s.snap_install_count

    # ------------------------------------------- deliver: install-snapshot
    # Payload (boundary, snapshot term, service state) is the sender's live
    # snapshot at delivery; a dead sender = a lost message (state.py
    # rationale). The message's LEADER term deposes stale leaders exactly
    # like AE/RV traffic, and only the current term's leader may install.
    k_snreset = jax.random.fold_in(key, _S_SNRESET)
    for src in range(n):
        arr = (s.sn_req_t[:, src] == t) & alive & alive[src]
        delivered += jnp.sum(arr, dtype=I32)
        mterm = s.sn_req_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        acc = arr & (mterm == term)
        role = jnp.where(acc & (role == CANDIDATE), FOLLOWER, role)
        timer = jnp.where(  # current-leader contact resets the election timer
            acc, _timeout_draw(cfg, jax.random.fold_in(k_snreset, src), (n,)), timer
        )
        slen = s.base[src]
        sterm_snap = s.snap_term[src]
        # cond_install (raft.rs:153): ignore a snapshot behind our commit.
        inst = acc & (slen > commit)
        # keep a matching suffix (conditional install); otherwise discard log
        keep = inst & (log_len > slen) & (
            _term_at(log_term, snap_term, base, slen, cap) == sterm_snap
        )
        delta = jnp.where(inst, jnp.maximum(slen - base, 0), 0)
        log_term = jnp.where(inst[:, None], _shift_rows(log_term, delta, cap), log_term)
        log_val = jnp.where(inst[:, None], _shift_rows(log_val, delta, cap), log_val)
        log_len = jnp.where(inst, jnp.where(keep, log_len, slen), log_len)
        base = jnp.where(inst, slen, base)
        snap_term = jnp.where(inst, sterm_snap, snap_term)
        commit = jnp.where(inst, jnp.maximum(commit, slen), commit)
        compact_floor = jnp.where(inst, slen, compact_floor)
        snap_installed_src = jnp.where(inst, src, snap_installed_src)
        snap_installed_len = jnp.where(inst, slen, snap_installed_len)
        snap_install_count += jnp.sum(inst, dtype=I32)
    sn_req_t = jnp.where(s.sn_req_t == t, 0, s.sn_req_t)

    # ----------------------------------------------------- deliver: RV requests
    k_grant = jax.random.fold_in(key, _S_GRANT)
    for src in range(n):
        arr = (s.rv_req_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = s.rv_req_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        my_llt = jnp.where(
            log_len > base, _row_gather(log_term, log_len - base - 1, cap), snap_term
        )
        log_ok = (s.rv_req_llt[:, src] > my_llt) | (
            (s.rv_req_llt[:, src] == my_llt) & (s.rv_req_lli[:, src] >= log_len)
        )
        grant = arr & (mterm == term) & ((voted_for == -1) | (voted_for == src)) & log_ok
        voted_for = jnp.where(grant, src, voted_for)
        ks = jax.random.fold_in(k_grant, src)
        timer = jnp.where(grant, _timeout_draw(cfg, ks, (n,)), timer)
        delay, lost = _net_draws(cfg, jax.random.fold_in(jax.random.fold_in(key, _S_RVREQ), src), (n,))
        send = arr & adj[:, src] & ~lost
        rv_rsp_t = rv_rsp_t.at[src, :].set(jnp.where(send, t + delay, rv_rsp_t[src, :]))
        rv_rsp_term = rv_rsp_term.at[src, :].set(jnp.where(send, term, rv_rsp_term[src, :]))
        rv_rsp_granted = rv_rsp_granted.at[src, :].set(
            jnp.where(send, grant, rv_rsp_granted[src, :])
        )
    rv_req_t = jnp.where(s.rv_req_t == t, 0, s.rv_req_t)

    # ----------------------------------------------------- deliver: AE requests
    k_aereset = jax.random.fold_in(key, _S_AERESET)
    for src in range(n):
        arr = (s.ae_req_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = s.ae_req_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        acc = arr & (mterm == term)  # AppendEntries from the current-term leader
        role = jnp.where(acc & (role == CANDIDATE), FOLLOWER, role)
        timer = jnp.where(
            acc, _timeout_draw(cfg, jax.random.fold_in(k_aereset, src), (n,)), timer
        )
        prev = s.ae_req_prev[:, src]
        # prev at-or-below our snapshot boundary is committed => matches by
        # definition; otherwise the terms must agree (log-matching check).
        prev_ok = (prev <= log_len) & (
            (prev <= base)
            | (_term_at(log_term, snap_term, base, prev, cap)
               == s.ae_req_prev_term[:, src])
        )
        success = acc & prev_ok
        nent = s.ae_req_n[:, src]
        conflict_any = jnp.zeros((n,), jnp.bool_)
        for e in range(ae_max):
            abs_idx = prev + e + 1          # 1-based absolute index of entry e
            slot = abs_idx - base - 1       # window slot
            in_batch = success & (e < nent) & (slot >= 0) & (slot < cap)
            ent_t = s.ae_req_ent_term[:, src, e]
            ent_v = s.ae_req_ent_val[:, src, e]
            conflict_any |= in_batch & (abs_idx <= log_len) & (
                _row_gather(log_term, slot, cap) != ent_t
            )
            cslot = jnp.clip(slot, 0, cap - 1)
            log_term = log_term.at[me, cslot].set(
                jnp.where(in_batch, ent_t, log_term[me, cslot])
            )
            log_val = log_val.at[me, cslot].set(
                jnp.where(in_batch, ent_v, log_val[me, cslot])
            )
        batch_end = jnp.minimum(prev + nent, base + cap)  # window overflow: drop tail
        # Conflict => truncate to the rewritten batch; otherwise never shrink
        # (a heartbeat must not drop entries a newer AE already appended).
        log_len = jnp.where(
            success,
            jnp.where(conflict_any, batch_end, jnp.maximum(log_len, batch_end)),
            log_len,
        )
        commit = jnp.where(
            success,
            jnp.maximum(commit, jnp.minimum(s.ae_req_commit[:, src], batch_end)),
            commit,
        )
        # Failure hint for fast backtracking (term-skip): first index of the
        # conflicting term, or our log length if the leader's prev is past our end.
        over = prev > log_len
        conf_term = _term_at(log_term, snap_term, base, prev, cap)
        first_slot = jnp.argmax(log_term == conf_term[:, None], axis=1).astype(I32)
        hint = jnp.where(over, log_len, jnp.maximum(base + first_slot, base))
        rsp_match = jnp.where(success, batch_end, hint)
        delay, lost = _net_draws(cfg, jax.random.fold_in(jax.random.fold_in(key, _S_AEREQ), src), (n,))
        send = arr & adj[:, src] & ~lost
        ae_rsp_t = ae_rsp_t.at[src, :].set(jnp.where(send, t + delay, ae_rsp_t[src, :]))
        ae_rsp_term = ae_rsp_term.at[src, :].set(jnp.where(send, term, ae_rsp_term[src, :]))
        ae_rsp_success = ae_rsp_success.at[src, :].set(
            jnp.where(send, success, ae_rsp_success[src, :])
        )
        ae_rsp_match = ae_rsp_match.at[src, :].set(
            jnp.where(send, rsp_match, ae_rsp_match[src, :])
        )
    ae_req_t = jnp.where(s.ae_req_t == t, 0, s.ae_req_t)

    # ---------------------------------------------------- deliver: RV responses
    for src in range(n):
        arr = (rv_rsp_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = rv_rsp_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        got = arr & rv_rsp_granted[:, src] & (role == CANDIDATE) & (mterm == term)
        votes = votes.at[:, src].set(votes[:, src] | got)
    rv_rsp_t = jnp.where(rv_rsp_t <= t, 0, rv_rsp_t)

    # ---------------------------------------------------- deliver: AE responses
    for src in range(n):
        arr = (ae_rsp_t[:, src] == t) & alive
        delivered += jnp.sum(arr, dtype=I32)
        mterm = ae_rsp_term[:, src]
        higher = arr & (mterm > term)
        term = jnp.where(higher, mterm, term)
        role = jnp.where(higher, FOLLOWER, role)
        voted_for = jnp.where(higher, -1, voted_for)
        ok = arr & (role == LEADER) & (mterm == term)
        succ = ok & ae_rsp_success[:, src]
        fail = ok & ~ae_rsp_success[:, src]
        m = ae_rsp_match[:, src]
        match_idx = match_idx.at[:, src].set(
            jnp.where(succ, jnp.maximum(match_idx[:, src], m), match_idx[:, src])
        )
        nxt = jnp.where(
            succ,
            jnp.maximum(next_idx[:, src], m + 1),
            jnp.where(fail, jnp.maximum(jnp.minimum(next_idx[:, src], m + 1), 1), next_idx[:, src]),
        )
        next_idx = next_idx.at[:, src].set(nxt)
    ae_rsp_t = jnp.where(ae_rsp_t <= t, 0, ae_rsp_t)

    # Candidate -> leader on majority (election win; raft.rs:286-292 drain path).
    win = alive & (role == CANDIDATE) & (jnp.sum(votes, axis=1) >= cfg.majority)
    role = jnp.where(win, LEADER, role)
    next_idx = jnp.where(win[:, None], log_len[:, None] + 1, next_idx)
    match_idx = jnp.where(win[:, None], 0, match_idx)
    hb = jnp.where(win, 0, hb)  # announce leadership with an immediate heartbeat

    # ------------------------------------------------- timers: election timeout
    kt = jax.random.split(jax.random.fold_in(key, _S_TIMER), 3)
    running = alive & (role != LEADER)
    timer = jnp.where(running, timer - 1, timer)
    fired = running & (timer <= 0)
    term = jnp.where(fired, term + 1, term)
    role = jnp.where(fired, CANDIDATE, role)
    voted_for = jnp.where(fired, me, voted_for)
    votes = jnp.where(fired[:, None], eye, votes)
    timer = jnp.where(fired, _timeout_draw(cfg, kt[0], (n,)), timer)

    llt = jnp.where(
        log_len > base, _row_gather(log_term, log_len - base - 1, cap), snap_term
    )
    delay, lost = _net_draws(cfg, kt[1], (n, n))
    send_rv = fired[None, :] & ~eye & adj.T & ~lost  # [dst, src], link src->dst
    rv_req_t = jnp.where(send_rv, t + delay, rv_req_t)
    rv_req_term = jnp.where(send_rv, term[None, :], s.rv_req_term)
    rv_req_lli = jnp.where(send_rv, log_len[None, :], s.rv_req_lli)
    rv_req_llt = jnp.where(send_rv, llt[None, :], s.rv_req_llt)

    # --------------------------------------- client command injection at leaders
    lead = alive & (role == LEADER)
    inject = (
        lead
        & jax.random.bernoulli(jax.random.fold_in(key, _S_CLIENT), cfg.p_client_cmd, (n,))
        & (log_len - base < cap)
    )
    slot = jnp.clip(log_len - base, 0, cap - 1)
    cmd_val = s.next_cmd * n + me + 1  # unique within the cluster, never 0
    log_term = log_term.at[me, slot].set(jnp.where(inject, term, log_term[me, slot]))
    log_val = log_val.at[me, slot].set(jnp.where(inject, cmd_val, log_val[me, slot]))
    log_len = jnp.where(inject, log_len + 1, log_len)
    next_cmd = s.next_cmd + jnp.any(inject).astype(I32)

    # -------------------------------------------- leader heartbeat / replication
    hb = jnp.where(lead, hb - 1, hb)
    fire_hb = lead & (hb <= 0)
    hb = jnp.where(fire_hb, cfg.heartbeat_ticks, hb)
    # A peer behind the leader's snapshot boundary gets an install-snapshot
    # trigger instead of entries (raft.rs:159 InstallSnapshot).
    need_snap = next_idx.T <= base[None, :]  # [dst, src]
    prev_m = next_idx.T - 1  # [dst, src]: src's prev index for dst
    n_m = jnp.clip(log_len[None, :] - prev_m, 0, ae_max)
    # entry e for (dst, src): src window slot (prev - base_src) + e
    slot0 = prev_m - base[None, :]
    idxs = slot0[:, :, None] + jnp.arange(ae_max, dtype=I32)[None, None, :]
    log_t_b = jnp.broadcast_to(log_term[None, :, :], (n, n, cap))
    log_v_b = jnp.broadcast_to(log_val[None, :, :], (n, n, cap))
    ent_t = jnp.take_along_axis(log_t_b, jnp.clip(idxs, 0, cap - 1), axis=2)
    ent_v = jnp.take_along_axis(log_v_b, jnp.clip(idxs, 0, cap - 1), axis=2)
    prev_term_m = jnp.where(
        prev_m > base[None, :],
        jnp.take_along_axis(
            log_t_b, jnp.clip(slot0 - 1, 0, cap - 1)[:, :, None], axis=2
        )[:, :, 0],
        snap_term[None, :],
    )
    delay, lost = _net_draws(cfg, jax.random.fold_in(key, _S_HB), (n, n))
    send_ae = fire_hb[None, :] & ~eye & adj.T & ~lost & ~need_snap
    ae_req_t = jnp.where(send_ae, t + delay, ae_req_t)
    ae_req_term = jnp.where(send_ae, term[None, :], s.ae_req_term)
    ae_req_prev = jnp.where(send_ae, prev_m, s.ae_req_prev)
    ae_req_prev_term = jnp.where(send_ae, prev_term_m, s.ae_req_prev_term)
    ae_req_n = jnp.where(send_ae, n_m, s.ae_req_n)
    ae_req_commit = jnp.where(send_ae, commit[None, :], s.ae_req_commit)
    ae_req_ent_term = jnp.where(send_ae[:, :, None], ent_t, s.ae_req_ent_term)
    ae_req_ent_val = jnp.where(send_ae[:, :, None], ent_v, s.ae_req_ent_val)
    delay_sn, lost_sn = _net_draws(cfg, jax.random.fold_in(key, _S_SNREQ), (n, n))
    send_sn = fire_hb[None, :] & ~eye & adj.T & ~lost_sn & need_snap
    sn_req_t = jnp.where(send_sn, t + delay_sn, sn_req_t)
    sn_req_term = jnp.where(send_sn, term[None, :], s.sn_req_term)
    # advance next_idx past the snapshot on send (retried via hints if lost)
    next_idx = jnp.where(send_sn.T, base[:, None] + 1, next_idx)

    # ------------------------------------------------------------ commit advance
    mi = match_idx.at[me, me].set(log_len)
    kth = -jnp.sort(-mi, axis=1)[:, cfg.majority - 1]  # majority-th largest match
    cur_term_ok = (kth > base) & (
        _term_at(log_term, snap_term, base, kth, cap) == term
    )
    commit = jnp.where(lead & cur_term_ok, jnp.maximum(commit, kth), commit)

    # ------------------------------------------------------------------- oracle
    viol = jnp.asarray(0, I32)
    # Election safety: two live leaders sharing a term (tester.rs:81-83).
    is_lead = alive & (role == LEADER)
    dual = (
        is_lead[:, None] & is_lead[None, :] & ~eye & (term[:, None] == term[None, :])
    )
    viol |= jnp.where(jnp.any(dual), VIOLATION_DUAL_LEADER, 0)
    # Log matching: same (index, term) => identical prefix, over the window
    # overlap of each pair (entries below either base are committed and are
    # covered by the shadow oracle). Align j's window onto i's slots.
    ks_ = jnp.arange(cap, dtype=I32)
    abs_i = base[:, None, None] + ks_[None, None, :] + 1          # [i, 1, k]
    j_slot = abs_i - base[None, :, None] - 1                      # [i, j, k]
    log_t_bj = jnp.broadcast_to(log_term[None, :, :], (n, n, cap))
    log_v_bj = jnp.broadcast_to(log_val[None, :, :], (n, n, cap))
    term_j = jnp.take_along_axis(log_t_bj, jnp.clip(j_slot, 0, cap - 1), axis=2)
    val_j = jnp.take_along_axis(log_v_bj, jnp.clip(j_slot, 0, cap - 1), axis=2)
    both = (
        (abs_i <= jnp.minimum(log_len[:, None], log_len[None, :])[:, :, None])
        & (j_slot >= 0) & (j_slot < cap)
    )
    tmatch = both & (log_term[:, None, :] == term_j)
    eq = tmatch & (log_val[:, None, :] == val_j)
    pref = jnp.cumprod((eq | ~both).astype(I32), axis=2).astype(jnp.bool_)
    viol |= jnp.where(jnp.any(tmatch & ~pref), VIOLATION_LOG_MATCHING, 0)
    # Commit durability: every entry any node ever committed is recorded in a
    # windowed shadow log; later commits must agree (catches Figure-8-style
    # commit loss; the online analogue of push_and_check, tester.rs:379-397).
    shadow_term, shadow_val = s.shadow_term, s.shadow_val
    shadow_base, shadow_len = s.shadow_base, s.shadow_len
    # slide the shadow window so the largest commit fits
    need = jnp.max(jnp.where(alive, commit, 0))
    sh_delta = jnp.maximum(need - cap - shadow_base, 0)
    shadow_term = jnp.where(
        sh_delta > 0,
        jnp.take(shadow_term, jnp.clip(ks_ + sh_delta, 0, cap - 1)),
        shadow_term,
    )
    shadow_val = jnp.where(
        sh_delta > 0,
        jnp.take(shadow_val, jnp.clip(ks_ + sh_delta, 0, cap - 1)),
        shadow_val,
    )
    shadow_base = shadow_base + sh_delta
    for i in range(n):
        c = commit[i]
        abs_k = shadow_base + ks_ + 1                 # shadow slot k's index
        i_slot = abs_k - base[i] - 1
        vis = (i_slot >= 0) & (i_slot < cap)
        node_t = jnp.take(log_term[i], jnp.clip(i_slot, 0, cap - 1))
        node_v = jnp.take(log_val[i], jnp.clip(i_slot, 0, cap - 1))
        known = vis & (abs_k <= jnp.minimum(c, shadow_len))
        differ = known & ((shadow_term != node_t) | (shadow_val != node_v))
        viol |= jnp.where(jnp.any(differ), VIOLATION_COMMIT_SHADOW, 0)
        new = vis & (abs_k > shadow_len) & (abs_k <= c)
        shadow_term = jnp.where(new, node_t, shadow_term)
        shadow_val = jnp.where(new, node_v, shadow_val)
        shadow_len = jnp.maximum(shadow_len, c)

    violations = s.violations | viol
    first_violation_tick = jnp.where(
        (s.first_violation_tick < 0) & (viol != 0), t, s.first_violation_tick
    )
    first_leader_tick = jnp.where(
        (s.first_leader_tick < 0) & jnp.any(is_lead), t, s.first_leader_tick
    )

    # -------------------------------------------------------------- compaction
    # AFTER the oracle on purpose: the shadow must record entries committed
    # this tick before the window discards them. Snapshot through the boundary
    # (commit, or the service layer's apply cursor) once compact_every entries
    # accumulated past base. Service layers observe base advancing and capture
    # their own state (kv.py); for pure raft the shadow is the only consumer.
    boundary = commit if cfg.compact_at_commit else jnp.minimum(compact_floor, commit)
    do_compact = alive & (boundary - base >= cfg.compact_every)
    delta = jnp.where(do_compact, boundary - base, 0)
    new_snap_term = _term_at(log_term, snap_term, base, boundary, cap)
    log_term = jnp.where(do_compact[:, None], _shift_rows(log_term, delta, cap), log_term)
    log_val = jnp.where(do_compact[:, None], _shift_rows(log_val, delta, cap), log_val)
    snap_term = jnp.where(do_compact, new_snap_term, snap_term)
    base = jnp.where(do_compact, boundary, base)

    return ClusterState(
        tick=t,
        term=term, voted_for=voted_for, role=role, timer=timer, hb=hb, alive=alive,
        log_term=log_term, log_val=log_val, log_len=log_len,
        base=base, snap_term=snap_term, commit=commit, compact_floor=compact_floor,
        votes=votes, next_idx=next_idx, match_idx=match_idx, adj=adj,
        rv_req_t=rv_req_t, rv_req_term=rv_req_term,
        rv_req_lli=rv_req_lli, rv_req_llt=rv_req_llt,
        rv_rsp_t=rv_rsp_t, rv_rsp_term=rv_rsp_term, rv_rsp_granted=rv_rsp_granted,
        ae_req_t=ae_req_t, ae_req_term=ae_req_term, ae_req_prev=ae_req_prev,
        ae_req_prev_term=ae_req_prev_term, ae_req_n=ae_req_n,
        ae_req_commit=ae_req_commit,
        ae_req_ent_term=ae_req_ent_term, ae_req_ent_val=ae_req_ent_val,
        ae_rsp_t=ae_rsp_t, ae_rsp_term=ae_rsp_term,
        ae_rsp_success=ae_rsp_success, ae_rsp_match=ae_rsp_match,
        sn_req_t=sn_req_t,
        sn_req_term=sn_req_term,
        snap_installed_src=snap_installed_src,
        snap_installed_len=snap_installed_len,
        next_cmd=next_cmd,
        shadow_term=shadow_term, shadow_val=shadow_val,
        shadow_base=shadow_base, shadow_len=shadow_len,
        violations=violations, first_violation_tick=first_violation_tick,
        first_leader_tick=first_leader_tick,
        msg_count=s.msg_count + delivered,
        snap_install_count=snap_install_count,
    )
