"""The state-of-the-world of one simulated Raft cluster as a pytree of dense arrays.

One ``ClusterState`` holds every node's Raft state plus the in-flight network as
single-slot per-(dst, src) mailbox tensors. ``jax.vmap`` over a leading cluster axis
turns this into the batched fuzzer state (tens of thousands of independent clusters).

Design notes (vs the reference, SURVEY.md §2.6/§7):
- Persistent state (term, voted_for, log) *is* the array — the lockstep phase order
  (state updates happen before message emission within a tick) gives the
  persist-before-send ordering the reference gets from fsync-before-reply
  (/root/reference/src/raft/raft.rs:224-233). Crash keeps these arrays; restart only
  resets volatile fields (role, timers, votes, commit, next/match).
- DURABILITY is modeled separately from the arrays (the madsim ``fs`` axis:
  crash/restore with partially durable files): ``durable_len`` plus the
  ``durable_term``/``durable_voted_for`` shadows are the per-node fsync
  watermark — what has actually reached disk. The correct algorithm fsyncs
  before any state-exposing emission (reply/broadcast/append-at-leader,
  step.py) and every ``fsync_every`` ticks in the background; a crash with
  ``p_lose_unsynced`` rolls term/voted_for/log_len back to the watermark
  (the un-fsynced suffix is the page cache lost at power-off). Compaction
  and install-snapshot persist in the reference (raft.rs snapshot()/
  cond_install_snapshot), so ``base``/``snap_term``/``prefix_hash`` are
  durable by construction and need no shadows.
- The network is modeled like madsim's per-message loss/latency draws
  (/root/reference/src/raft/tester.rs:127-137): each directed (dst, src) pair has one
  slot per message type with a delivery tick; overwriting an undelivered slot models
  packet loss (counted faithfully as Raft must tolerate it).
- Log indices are 1-based as in Raft. The log array is a CANONICAL RING:
  absolute index ``a`` always lives in lane ``(a - 1) mod log_cap``, ``base`` is
  the snapshot boundary (indices 1..base are compacted away; the live window is
  ``(base, base + log_cap]``), and ``log_len`` / ``commit`` stay ABSOLUTE
  (highest index present / committed). ``snap_term`` is the term at index
  ``base``. Because an index's lane never changes, compaction and
  install-snapshot are pure ``base`` bumps — no data movement — and every
  access is a lane-vectorized one-hot select (per-row dynamic gathers/shifts
  serialize on TPU). This is what lets fuzz histories run far past ``log_cap``
  (SURVEY.md §5: "long histories → fixed-size buffers + on-device compaction").
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import (
    FOLLOWER,
    NOOP_CMD,
    SimConfig,
    metrics_dims,
    packed_bounds,
)

I32 = jnp.int32
BOOL = jnp.bool_
U8 = jnp.uint8
U16 = jnp.uint16
U32 = jnp.uint32

# Trace/replay artifact schema version (MIGRATION.md "State layout"):
#   1 — the wide layout: every ClusterState field i32 (or padded bool)
#   2 — the packed cold-state schema below (PackedClusterState): narrow
#       dtypes derived from config.packed_bounds, bitfield words for
#       role/alive/adjacency/votes, tick-relative u8 mailbox stamps.
#   3 — the service-layer packed schemas (ISSUE 11): kv/ctrler/shardkv
#       carries pack under the same exact-or-wide rule (PackedKvState /
#       PackedCtrlerState / PackedShardKvState in their own modules), with
#       the embedded raft group's index/cmd dtypes re-derived for the
#       service append rate via packed_spec_for (a service tick can append
#       up to n_clients (+ marker) entries per node, so the raft layer's
#       2-per-tick index bound does not hold there).
#   4 — the tail-latency attribution plane (ISSUE 12): metrics-on states
#       and reports gain per-phase histograms (phase_hist/phase_ticks/
#       lat_ticks), the worst-op register (worst_*), and — on the service
#       layers — per-key/per-client latency axes; report surfaces gain
#       `latency.phases` / `latency_phases` / `worst_op` fields. Metrics-
#       off layouts are byte-identical to v3.
# Replay/explain JSON carries this plus the layout the run actually used.
STATE_SCHEMA_VERSION = 4


class ClusterState(NamedTuple):
    """All arrays for a single cluster (vmap adds the cluster axis)."""

    tick: jax.Array            # i32 scalar: current tick
    # --- per-node Raft state [N] ---
    term: jax.Array            # i32 current term (persistent)
    voted_for: jax.Array       # i32, -1 = none (persistent)
    role: jax.Array            # i32: 0 follower / 1 candidate / 2 leader
    timer: jax.Array           # i32 ticks until election timeout
    hb: jax.Array              # i32 ticks until next leader heartbeat
    alive: jax.Array           # bool
    # --- gray-failure state (ISSUE 19; neutral values 1 / 0) ---
    limp: jax.Array            # i32 [N] delivery-delay multiplier for this
    #                            node's sends (1 = healthy; in
    #                            [2, limp_mult_max] while limping; restart
    #                            clears it — step.py faults phase)
    fsync_stall: jax.Array     # i32 [N] remaining ticks the background
    #                            fsync cadence is stalled (0 = none; the
    #                            explicit persist-before-* syncs are never
    #                            stalled — see config.p_fsync_stall)
    # --- log window [N, CAP] (persistent; slot k = absolute index base+k+1) ---
    log_term: jax.Array        # i32
    log_val: jax.Array         # i32 (commands are unique ints)
    log_len: jax.Array         # i32 [N] absolute length (highest index present)
    base: jax.Array            # i32 [N] snapshot boundary (persistent)
    snap_term: jax.Array       # i32 [N] term at index `base` (persistent)
    prefix_hash: jax.Array     # i32 [N] order-free hash of entries 1..base
    #                            (persistent; folded at compaction, adopted at
    #                            install-snapshot) — lets the durability oracle
    #                            see divergence on entries older than the
    #                            window (step.py prefix-divergence check)
    commit: jax.Array          # i32 [N] committed count, absolute (volatile)
    # --- fsync watermark (what has reached disk; see module docstring) ---
    durable_len: jax.Array       # i32 [N] highest fsynced log index (absolute);
    #                              invariants: base <= durable_len <= log_len
    durable_term: jax.Array      # i32 [N] fsynced shadow of `term`
    durable_voted_for: jax.Array  # i32 [N] fsynced shadow of `voted_for`
    compact_floor: jax.Array   # i32 [N] service-layer cap on the compaction
    #                            boundary (= its apply cursor); unused when
    #                            cfg.compact_at_commit
    # --- candidate / leader bookkeeping ---
    votes: jax.Array           # bool [N, N]: votes[i, j] = candidate i holds j's grant
    next_idx: jax.Array        # i32 [N, N]: leader i's next index for peer j (1-based)
    match_idx: jax.Array       # i32 [N, N]: leader i's known match count for peer j
    # --- network ---
    adj: jax.Array             # bool [N, N] directed link usable (diag True)
    # RequestVote request mailbox [dst, src]
    rv_req_t: jax.Array        # i32 delivery tick; 0 = empty
    rv_req_term: jax.Array
    rv_req_lli: jax.Array      # candidate last log index (count)
    rv_req_llt: jax.Array      # candidate last log term
    # RequestVote response mailbox [dst(candidate), src(voter)]
    rv_rsp_t: jax.Array
    rv_rsp_term: jax.Array
    rv_rsp_granted: jax.Array  # bool
    # AppendEntries request mailbox [dst, src]
    ae_req_t: jax.Array
    ae_req_term: jax.Array
    ae_req_prev: jax.Array     # prev log index (count before batch)
    ae_req_prev_term: jax.Array
    ae_req_n: jax.Array        # entries carried (<= ae_max); the entry
    #                            payload itself is read from the sender's
    #                            live log at delivery (read-at-delivery, see
    #                            step.py AE delivery) — no entry mailboxes
    ae_req_commit: jax.Array   # leader commit
    # AppendEntries response mailbox [dst(leader), src(follower)]
    ae_rsp_t: jax.Array
    ae_rsp_term: jax.Array
    ae_rsp_success: jax.Array  # bool
    ae_rsp_match: jax.Array    # success: new match count; failure: next-index hint - 1
    # InstallSnapshot trigger mailbox [dst, src] (raft.rs:149-168). The payload
    # (boundary, snapshot term, service state) is read from the SENDER's live
    # snapshot at delivery — semantically the snapshot "sent at delivery
    # instant"; a dead sender at delivery = a lost message. The LEADER term
    # rides in the message (sn_req_term): like every RPC it deposes stale
    # leaders, and an install is only accepted from the current term's leader
    # — otherwise a deposed leader could truncate its fork and re-mint old
    # indices in its stale term, breaking log matching. Install outcome is
    # surfaced to service layers via snap_installed_src/len below.
    sn_req_t: jax.Array
    sn_req_term: jax.Array
    snap_installed_src: jax.Array  # i32 [N]: src installed from this tick (-1)
    snap_installed_len: jax.Array  # i32 [N]: boundary adopted this tick
    # --- workload / oracle ---
    next_cmd: jax.Array        # i32 scalar: per-cluster unique command counter
    # Committed-entry shadow (durability oracle) — windowed like the logs:
    # slot k = absolute index shadow_base+k+1; shadow_len is absolute.
    shadow_term: jax.Array     # i32 [CAP]
    shadow_val: jax.Array      # i32 [CAP]
    shadow_base: jax.Array     # i32 scalar
    shadow_len: jax.Array      # i32 scalar
    shadow_prefix_hash: jax.Array  # i32 scalar: hash of entries slid out of
    #                                the shadow window (same fold as nodes)
    violations: jax.Array      # i32 scalar sticky bitmask
    first_violation_tick: jax.Array  # i32 scalar, -1 = none
    first_leader_tick: jax.Array     # i32 scalar, -1 = none (liveness metric)
    msg_count: jax.Array       # i32 scalar: delivered messages (tester.rs:147-149)
    snap_install_count: jax.Array  # i32 scalar: snapshot installs (2D metric)
    # --- on-device metrics plane (ISSUE 10; shapes from config.metrics_dims
    # — ALL ZERO-SIZE with cfg.metrics off, so the metrics-off state carries
    # zero extra bytes and every metrics-off program is untouched) ---
    log_tick: jax.Array        # i32 [N, CAP]: submit stamp of each live log
    #                            entry — the tick a RAFT-INJECTED client
    #                            command was first appended at its leader.
    #                            Replicated with the entry at AE delivery;
    #                            0 for leader no-ops and for every service-
    #                            layer entry (kv/shardkv stamp their clerks
    #                            instead and fold at clerk-ack), so the
    #                            shadow fold's stamp > 0 mask counts each
    #                            injected command exactly once
    shadow_sub: jax.Array      # i32 [CAP] per-TICK scratch: submit stamps of
    #                            the entries the durability shadow recorded
    #                            THIS tick (0 = lane not recorded / not a
    #                            stamped client op). Reset every tick — the
    #                            flight recorder snapshots it, which is what
    #                            makes host-recomputed latencies exact
    lat_hist: jax.Array        # i32 [HIST_BUCKETS]: submit->ack latency
    #                            histogram, fixed log-spaced buckets
    #                            (metrics.py layout); raft layer folds at
    #                            commit (shadow append), service layers at
    #                            clerk ack — merged across lanes/shards by
    #                            plain addition
    ev_counts: jax.Array       # i32 [len(METRIC_EVENTS)]: cumulative
    #                            per-lane liveness-event counters in
    #                            config.METRIC_EVENTS order
    # --- tail-latency attribution plane (ISSUE 12; all zero-size with
    # cfg.metrics off, incl. the "scalar" register fields, which are [1]
    # arrays so the off-shape is [0], not a real scalar) ---
    phase_hist: jax.Array      # i32 [n_phases, HIST_BUCKETS]: per-phase
    #                            duration histograms (config.LATENCY_PHASES
    #                            order); every acked op folds one sample
    #                            into EVERY row (zeros land in bucket 0),
    #                            so each row's mass == acked ops
    phase_ticks: jax.Array     # i32 [n_phases]: exact cumulative duration
    #                            per phase; sum == lat_ticks (the pinned
    #                            phase-sum==latency invariant, aggregated)
    lat_ticks: jax.Array       # i32 [1]: exact cumulative end-to-end
    #                            latency ticks across all folded acks
    worst_lat: jax.Array       # i32 [1]: argmax-latency op's latency
    worst_phases: jax.Array    # i32 [n_phases]: its phase vector (sums to
    #                            worst_lat exactly — the per-op proof the
    #                            invariant test reads)
    worst_key: jax.Array       # i32 [1]: its key (-1 for raft commands)
    worst_client: jax.Array    # i32 [1]: its client (-1 for raft commands)
    worst_sub: jax.Array       # i32 [1]: its submit tick (0 = register
    #                            empty; real stamps are >= 1)


def durable_after_append(s: ClusterState, new_len: jax.Array) -> jax.Array:
    """Fsync watermark after a service-layer submit batch: submits model
    RaftHandle::start -> persist-at-append (raft.rs:311-313 — the leader's
    own log is commit-counted, so it must be durable), so the watermark
    follows the log where it grew. The single source of the rule for every
    service layer's submit path (kv/ctrler/shardkv)."""
    return jnp.where(new_len > s.log_len, new_len, s.durable_len)


def abstract_node_tuple(
    s: ClusterState, term_rank_levels: int, commit_delta_levels: int
) -> tuple:
    """The per-node abstract-state observation the coverage subsystem
    fingerprints (coverage.py, ROADMAP item 3) — defined here, next to the
    state it reads, so extending the abstraction means touching this tuple
    rather than the engine. Each component is quantized to a tiny static
    alphabet so the folded code space of a small cluster stays enumerable:

    - role:          0 follower / 1 candidate / 2 leader
    - alive:         0 / 1
    - term-rank:     #nodes with a strictly smaller term, clipped to
                     ``term_rank_levels - 1`` — captures WHO is ahead in the
                     term order, not by how much (absolute terms grow
                     without bound; their order pattern is what
                     distinguishes interleavings)
    - commit-delta:  ``commit - min(commit)`` clipped to
                     ``commit_delta_levels - 1`` — who lags the commit
                     frontier (the Figure-8 family lives in these lags)

    Returns four i32 ``[n]`` arrays (vmap adds the lane axis).
    """
    rank = jnp.clip(
        jnp.sum(s.term[None, :] < s.term[:, None], axis=1).astype(I32),
        0, term_rank_levels - 1,
    )
    delta = jnp.clip(s.commit - jnp.min(s.commit), 0, commit_delta_levels - 1)
    return s.role, s.alive.astype(I32), rank, delta


def init_cluster(cfg: SimConfig, key: jax.Array, kn=None) -> ClusterState:
    """Fresh cluster at tick 0 with randomized election timers (raft.rs:260-263).

    ``kn`` (a ``config.Knobs``) carries the dynamic knobs as traced scalars;
    omitted, they are baked from ``cfg`` as constants (single-config callers).
    """
    if kn is None:
        kn = cfg.knobs()
    n, cap = cfg.n_nodes, cfg.log_cap
    hb, evn, mcap, nph, reg = metrics_dims(cfg)
    zn = jnp.zeros((n,), I32)
    znn = jnp.zeros((n, n), I32)
    timer = jax.random.randint(
        key, (n,), kn.eto_min, kn.eto_max + 1, dtype=I32
    ) + jnp.arange(n, dtype=I32) * jnp.asarray(kn.eto_skew, I32)
    # (the gray clock-skew offset — adding the zero neutral knob leaves
    # the i32 draw bit-identical, and the draw itself is unchanged)
    return ClusterState(
        tick=jnp.asarray(0, I32),
        term=zn,
        voted_for=jnp.full((n,), -1, I32),
        role=jnp.full((n,), FOLLOWER, I32),
        timer=timer,
        hb=zn,
        alive=jnp.ones((n,), BOOL),
        limp=jnp.ones((n,), I32),
        fsync_stall=zn,
        log_term=jnp.zeros((n, cap), I32),
        log_val=jnp.zeros((n, cap), I32),
        log_len=zn,
        base=zn,
        snap_term=zn,
        prefix_hash=zn,
        commit=zn,
        durable_len=zn,
        durable_term=zn,
        durable_voted_for=jnp.full((n,), -1, I32),
        compact_floor=zn,
        votes=jnp.zeros((n, n), BOOL),
        next_idx=jnp.ones((n, n), I32),
        match_idx=znn,
        adj=jnp.ones((n, n), BOOL),
        rv_req_t=znn, rv_req_term=znn, rv_req_lli=znn, rv_req_llt=znn,
        rv_rsp_t=znn, rv_rsp_term=znn, rv_rsp_granted=jnp.zeros((n, n), BOOL),
        ae_req_t=znn, ae_req_term=znn, ae_req_prev=znn, ae_req_prev_term=znn,
        ae_req_n=znn, ae_req_commit=znn,
        ae_rsp_t=znn, ae_rsp_term=znn,
        ae_rsp_success=jnp.zeros((n, n), BOOL), ae_rsp_match=znn,
        sn_req_t=znn,
        sn_req_term=znn,
        snap_installed_src=jnp.full((n,), -1, I32),
        snap_installed_len=zn,
        next_cmd=jnp.asarray(0, I32),
        shadow_term=jnp.zeros((cap,), I32),
        shadow_val=jnp.zeros((cap,), I32),
        shadow_base=jnp.asarray(0, I32),
        shadow_len=jnp.asarray(0, I32),
        shadow_prefix_hash=jnp.asarray(0, I32),
        violations=jnp.asarray(0, I32),
        first_violation_tick=jnp.asarray(-1, I32),
        first_leader_tick=jnp.asarray(-1, I32),
        msg_count=jnp.asarray(0, I32),
        snap_install_count=jnp.asarray(0, I32),
        log_tick=jnp.zeros((n, mcap), I32),
        shadow_sub=jnp.zeros((mcap,), I32),
        lat_hist=jnp.zeros((hb,), I32),
        ev_counts=jnp.zeros((evn,), I32),
        phase_hist=jnp.zeros((nph, hb), I32),
        phase_ticks=jnp.zeros((nph,), I32),
        lat_ticks=jnp.zeros((reg,), I32),
        worst_lat=jnp.zeros((reg,), I32),
        worst_phases=jnp.zeros((nph,), I32),
        worst_key=jnp.full((reg,), -1, I32),
        worst_client=jnp.full((reg,), -1, I32),
        worst_sub=jnp.zeros((reg,), I32),
    )


# ---------------------------------------------------------------------------
# Packed cold-state schema (ISSUE 9; ROADMAP item 5).
#
# The per-tick arithmetic above runs on i32 arrays — the wide layout. The
# CARRIED state (the fori_loop/scan carry of the chunk, pool, trace, and
# replay programs — what actually sits in HBM between ticks and between
# dispatches, double-buffered under donation) is this packed schema: every
# field narrowed to the smallest dtype its configured range admits
# (config.packed_bounds is the single source of those ranges), with
# widen-on-use at the step boundary (step.step_cluster_packed = pack o step
# o unpack), so the tick itself never touches a narrow dtype.
#
# Encodings beyond the plain casts:
#   role_bits / alive_bits  all nodes' role (2 bits each) / aliveness (1 bit)
#                           in ONE u32 word per cluster (n_nodes <= 16)
#   *_bits rows             [n, n] bool matrices (votes, adj, rv granted,
#                           ae success) as [n] u32 row bitmasks — bit j of
#                           row i = mat[i, j], the trace.TickRecord adj_mask
#                           convention
#   *_rel stamps            mailbox delivery ticks stored RELATIVE to the
#                           cluster tick in one u8 (0 = empty slot): every
#                           live slot holds a future tick and the per-send
#                           delay is < 256 (_net_draws), so stamp - tick in
#                           [1, 254] — see packed_layout_reason's delay gate
#   log_val / shadow_val    cmd payloads in the cmd-bound dtype, with
#                           NOOP_CMD (1 << 30, far outside any packed range)
#                           re-encoded as the dtype's reserved max value
#   voted_for / *_src       node ids incl. the -1 sentinel: plain i8
#
# Round-trip exactness (unpack_state(pack_state(s)) == s bit-for-bit, for
# every state whose values respect the configured bounds) is the load-
# bearing property — it is what keeps the golden fuzz/pool guards and the
# (seed, cluster_id) replay contract bit-identical on the packed path —
# and tests/test_state_layout.py pins it on randomized boundary-value
# states and on real trajectories.
# ---------------------------------------------------------------------------


class PackedSpec(NamedTuple):
    """Derived dtypes of the packed schema for one SimConfig (the widths
    tests pin against config.packed_bounds)."""

    tick: object        # dtype of tick/next_cmd (bound: packed_bounds.tick)
    term: object        # dtype of every term-valued field
    index: object       # dtype of every absolute log-index field
    cmd: object         # dtype of log_val/shadow_val payloads
    noop_code: int      # the cmd dtype's reserved encoding of NOOP_CMD
    tick_signed: object  # first_violation_tick / first_leader_tick (-1 ok)
    event: object       # dtype of the ev_counts liveness-counter row
    #                     (bound: packed_bounds.event = n_nodes * T)


def _uint_for(bound: int):
    """Smallest unsigned dtype holding [0, bound]."""
    for dt in (U8, U16, U32):
        if bound <= np.iinfo(dt).max:
            return dt
    raise ValueError(f"packed bound {bound} exceeds u32")


def _sint_for(bound: int):
    """Smallest signed dtype holding [-1, bound]."""
    for dt in (jnp.int8, jnp.int16, I32):
        if bound <= np.iinfo(dt).max:
            return dt
    raise ValueError(f"packed bound {bound} exceeds i32")


@functools.lru_cache(maxsize=None)
def packed_spec_for(cfg: SimConfig, index_bound: Optional[int] = None,
                    cmd_bound: Optional[int] = None) -> PackedSpec:
    """PackedSpec with the index/cmd bounds optionally OVERRIDDEN — the
    service-layer hook (ISSUE 11): a kv/ctrler/shardkv tick appends up to
    n_clients client entries (plus marker entries) per node, so the raft
    layer's 2-per-tick index bound and n*(T+1) cmd bound do not hold for
    the raft group embedded in a service carry. Each service module derives
    its own bounds from its static config and packs its raft sub-state with
    this spec; the default (both None) is exactly the raft-layer spec.

    Width regressions here are caught statically (ISSUE 15): the lint
    packed_width pass audits every cached program's carry dtypes against
    this spec, and tests/test_width_pin.py re-derives the minimal dtype
    per field from packed_bounds and pins the full field->dtype map."""
    b = packed_bounds(cfg)
    cmd_dt = _uint_for((b.cmd if cmd_bound is None else cmd_bound) + 1)
    # + 1 reserves a distinct NOOP sentinel
    return PackedSpec(
        tick=_uint_for(b.tick),
        term=_uint_for(b.term),
        index=_uint_for(b.index if index_bound is None else index_bound),
        cmd=cmd_dt,
        noop_code=int(np.iinfo(cmd_dt).max),
        tick_signed=_sint_for(b.tick),
        event=_uint_for(b.event),
    )


def packed_spec(cfg: SimConfig) -> PackedSpec:
    return packed_spec_for(cfg)


class PackedClusterState(NamedTuple):
    """ClusterState in the packed schema (field order mirrors the wide
    form; `_bits` = bitfield word(s), `_rel` = tick-relative u8 stamp)."""

    tick: jax.Array
    term: jax.Array
    voted_for: jax.Array        # i8, -1 sentinel intact
    role_bits: jax.Array        # u32 scalar: 2 bits per node
    timer: jax.Array            # u16 (eto_max gated by packed_layout_reason)
    hb: jax.Array               # u16
    alive_bits: jax.Array       # u32 scalar bitfield
    limp: jax.Array             # u8 (limp_mult_max gated <= 255; the
    #                             stretched delay gate keeps stamps in u8)
    fsync_stall: jax.Array      # u16 (fsync_stall_ticks gated <= 65535)
    log_term: jax.Array
    log_val: jax.Array          # cmd dtype; NOOP_CMD -> noop_code
    log_len: jax.Array
    base: jax.Array
    snap_term: jax.Array
    prefix_hash: jax.Array      # i32 — a full 32-bit hash stays wide
    commit: jax.Array
    durable_len: jax.Array
    durable_term: jax.Array
    durable_voted_for: jax.Array  # i8
    compact_floor: jax.Array
    votes_bits: jax.Array       # u32 [n] row masks
    next_idx: jax.Array
    match_idx: jax.Array
    adj_bits: jax.Array         # u32 [n] row masks
    rv_req_rel: jax.Array
    rv_req_term: jax.Array
    rv_req_lli: jax.Array
    rv_req_llt: jax.Array
    rv_rsp_rel: jax.Array
    rv_rsp_term: jax.Array
    rv_rsp_granted_bits: jax.Array  # u32 [n]
    ae_req_rel: jax.Array
    ae_req_term: jax.Array
    ae_req_prev: jax.Array
    ae_req_prev_term: jax.Array
    ae_req_n: jax.Array         # u8 (<= ae_max)
    ae_req_commit: jax.Array
    ae_rsp_rel: jax.Array
    ae_rsp_term: jax.Array
    ae_rsp_success_bits: jax.Array  # u32 [n]
    ae_rsp_match: jax.Array
    sn_req_rel: jax.Array
    sn_req_term: jax.Array
    snap_installed_src: jax.Array   # i8, -1 sentinel intact
    snap_installed_len: jax.Array
    next_cmd: jax.Array
    shadow_term: jax.Array
    shadow_val: jax.Array       # cmd dtype; NOOP_CMD -> noop_code
    shadow_base: jax.Array
    shadow_len: jax.Array
    shadow_prefix_hash: jax.Array   # i32
    violations: jax.Array           # i32 — shared across service layers
    first_violation_tick: jax.Array  # tick_signed
    first_leader_tick: jax.Array     # tick_signed
    msg_count: jax.Array            # i32 cumulative counter
    snap_install_count: jax.Array   # i32
    # --- metrics plane (ISSUE 10; zero-size with cfg.metrics off) ---
    log_tick: jax.Array             # tick dtype: per-entry submit stamps
    shadow_sub: jax.Array           # tick dtype: this-tick shadow stamps
    lat_hist: jax.Array             # index dtype: bucket counts — each
    #                                 bucket counts committed/acked ops,
    #                                 bounded by the spec's index bound (the
    #                                 raft bound on the raft path; the
    #                                 service layers pack with their own
    #                                 re-derived index bound, which covers
    #                                 their clerk-ack folds — ISSUE 11)
    ev_counts: jax.Array            # event dtype (narrow row; see
    #                                 packed_bounds.event)
    # --- attribution plane (ISSUE 12; zero-size with cfg.metrics off) ---
    phase_hist: jax.Array           # index dtype (per-phase bucket counts
    #                                 are bounded by acked ops, like
    #                                 lat_hist)
    phase_ticks: jax.Array          # i32 — a SUM of latencies (ops x T)
    #                                 can outgrow any per-op bound; full
    #                                 width by design, like msg_count
    lat_ticks: jax.Array            # i32 (same sum-of-latencies argument)
    worst_lat: jax.Array            # tick dtype (a latency is <= T)
    worst_phases: jax.Array         # tick dtype (each phase <= latency)
    worst_key: jax.Array            # i32 — service-layer key ids with a
    #                                 -1 sentinel; the raft spec cannot
    #                                 know the service key alphabet, so
    #                                 full width by design
    worst_client: jax.Array         # i32 (same)
    worst_sub: jax.Array            # tick dtype (a submit stamp, >= 0)


def _bit_weights(n: int) -> jax.Array:
    return jnp.left_shift(jnp.asarray(1, U32), jnp.arange(n, dtype=U32))


def _pack_bool_rows(mat: jax.Array) -> jax.Array:
    """[n, n] bool -> [n] u32 row masks (bit j of row i = mat[i, j])."""
    n = mat.shape[-1]
    return jnp.sum(
        jnp.where(mat, _bit_weights(n)[None, :], jnp.asarray(0, U32)),
        axis=-1, dtype=U32,
    )


def _unpack_bool_rows(rows: jax.Array, n: int) -> jax.Array:
    return (
        (rows[:, None] >> jnp.arange(n, dtype=U32)[None, :]) & 1
    ).astype(BOOL)


def pack_state(cfg: SimConfig, s: ClusterState,
               sp: Optional[PackedSpec] = None) -> PackedClusterState:
    """Wide -> packed, exact for every value within config.packed_bounds.
    Written per-cluster; the engine vmaps it over the lane axis. ``sp``
    lets a service layer substitute its re-derived spec (packed_spec_for);
    None keeps the raft-layer derivation."""
    if sp is None:
        sp = packed_spec(cfg)
    n = cfg.n_nodes
    t = s.tick
    idx = jnp.arange(n, dtype=U32)

    def rel(stamp):  # live stamps are strictly in the future (> tick)
        return jnp.where(stamp > 0, stamp - t, 0).astype(U8)

    noop = jnp.asarray(sp.noop_code, sp.cmd)

    def cmd(v):
        return jnp.where(v == NOOP_CMD, noop, v.astype(sp.cmd))

    return PackedClusterState(
        tick=s.tick.astype(sp.tick),
        term=s.term.astype(sp.term),
        voted_for=s.voted_for.astype(jnp.int8),
        role_bits=jnp.sum(s.role.astype(U32) << (2 * idx), dtype=U32),
        timer=s.timer.astype(U16),
        hb=s.hb.astype(U16),
        alive_bits=jnp.sum(
            jnp.where(s.alive, _bit_weights(n), jnp.asarray(0, U32)),
            dtype=U32,
        ),
        limp=s.limp.astype(U8),
        fsync_stall=s.fsync_stall.astype(U16),
        log_term=s.log_term.astype(sp.term),
        log_val=cmd(s.log_val),
        log_len=s.log_len.astype(sp.index),
        base=s.base.astype(sp.index),
        snap_term=s.snap_term.astype(sp.term),
        prefix_hash=s.prefix_hash,
        commit=s.commit.astype(sp.index),
        durable_len=s.durable_len.astype(sp.index),
        durable_term=s.durable_term.astype(sp.term),
        durable_voted_for=s.durable_voted_for.astype(jnp.int8),
        compact_floor=s.compact_floor.astype(sp.index),
        votes_bits=_pack_bool_rows(s.votes),
        next_idx=s.next_idx.astype(sp.index),
        match_idx=s.match_idx.astype(sp.index),
        adj_bits=_pack_bool_rows(s.adj),
        rv_req_rel=rel(s.rv_req_t),
        rv_req_term=s.rv_req_term.astype(sp.term),
        rv_req_lli=s.rv_req_lli.astype(sp.index),
        rv_req_llt=s.rv_req_llt.astype(sp.term),
        rv_rsp_rel=rel(s.rv_rsp_t),
        rv_rsp_term=s.rv_rsp_term.astype(sp.term),
        rv_rsp_granted_bits=_pack_bool_rows(s.rv_rsp_granted),
        ae_req_rel=rel(s.ae_req_t),
        ae_req_term=s.ae_req_term.astype(sp.term),
        ae_req_prev=s.ae_req_prev.astype(sp.index),
        ae_req_prev_term=s.ae_req_prev_term.astype(sp.term),
        ae_req_n=s.ae_req_n.astype(U8),
        ae_req_commit=s.ae_req_commit.astype(sp.index),
        ae_rsp_rel=rel(s.ae_rsp_t),
        ae_rsp_term=s.ae_rsp_term.astype(sp.term),
        ae_rsp_success_bits=_pack_bool_rows(s.ae_rsp_success),
        ae_rsp_match=s.ae_rsp_match.astype(sp.index),
        sn_req_rel=rel(s.sn_req_t),
        sn_req_term=s.sn_req_term.astype(sp.term),
        snap_installed_src=s.snap_installed_src.astype(jnp.int8),
        snap_installed_len=s.snap_installed_len.astype(sp.index),
        next_cmd=s.next_cmd.astype(sp.tick),
        shadow_term=s.shadow_term.astype(sp.term),
        shadow_val=cmd(s.shadow_val),
        shadow_base=s.shadow_base.astype(sp.index),
        shadow_len=s.shadow_len.astype(sp.index),
        shadow_prefix_hash=s.shadow_prefix_hash,
        violations=s.violations,
        first_violation_tick=s.first_violation_tick.astype(sp.tick_signed),
        first_leader_tick=s.first_leader_tick.astype(sp.tick_signed),
        msg_count=s.msg_count,
        snap_install_count=s.snap_install_count,
        log_tick=s.log_tick.astype(sp.tick),
        shadow_sub=s.shadow_sub.astype(sp.tick),
        lat_hist=s.lat_hist.astype(sp.index),
        ev_counts=s.ev_counts.astype(sp.event),
        phase_hist=s.phase_hist.astype(sp.index),
        phase_ticks=s.phase_ticks,
        lat_ticks=s.lat_ticks,
        worst_lat=s.worst_lat.astype(sp.tick),
        worst_phases=s.worst_phases.astype(sp.tick),
        worst_key=s.worst_key,
        worst_client=s.worst_client,
        worst_sub=s.worst_sub.astype(sp.tick),
    )


def unpack_state(cfg: SimConfig, p: PackedClusterState,
                 sp: Optional[PackedSpec] = None) -> ClusterState:
    """Packed -> wide (the widen-on-use boundary): exact inverse of
    pack_state, restoring the i32/bool dtypes step_cluster runs on."""
    if sp is None:
        sp = packed_spec(cfg)
    n = cfg.n_nodes
    t = p.tick.astype(I32)
    idx = jnp.arange(n, dtype=U32)

    def stamp(r):
        r32 = r.astype(I32)
        return jnp.where(r32 > 0, t + r32, 0)

    noop = jnp.asarray(sp.noop_code, sp.cmd)

    def cmd(v):
        return jnp.where(v == noop, NOOP_CMD, v.astype(I32))

    return ClusterState(
        tick=t,
        term=p.term.astype(I32),
        voted_for=p.voted_for.astype(I32),
        role=((p.role_bits >> (2 * idx)) & 3).astype(I32),
        timer=p.timer.astype(I32),
        hb=p.hb.astype(I32),
        alive=((p.alive_bits >> idx) & 1).astype(BOOL),
        limp=p.limp.astype(I32),
        fsync_stall=p.fsync_stall.astype(I32),
        log_term=p.log_term.astype(I32),
        log_val=cmd(p.log_val),
        log_len=p.log_len.astype(I32),
        base=p.base.astype(I32),
        snap_term=p.snap_term.astype(I32),
        prefix_hash=p.prefix_hash,
        commit=p.commit.astype(I32),
        durable_len=p.durable_len.astype(I32),
        durable_term=p.durable_term.astype(I32),
        durable_voted_for=p.durable_voted_for.astype(I32),
        compact_floor=p.compact_floor.astype(I32),
        votes=_unpack_bool_rows(p.votes_bits, n),
        next_idx=p.next_idx.astype(I32),
        match_idx=p.match_idx.astype(I32),
        adj=_unpack_bool_rows(p.adj_bits, n),
        rv_req_t=stamp(p.rv_req_rel),
        rv_req_term=p.rv_req_term.astype(I32),
        rv_req_lli=p.rv_req_lli.astype(I32),
        rv_req_llt=p.rv_req_llt.astype(I32),
        rv_rsp_t=stamp(p.rv_rsp_rel),
        rv_rsp_term=p.rv_rsp_term.astype(I32),
        rv_rsp_granted=_unpack_bool_rows(p.rv_rsp_granted_bits, n),
        ae_req_t=stamp(p.ae_req_rel),
        ae_req_term=p.ae_req_term.astype(I32),
        ae_req_prev=p.ae_req_prev.astype(I32),
        ae_req_prev_term=p.ae_req_prev_term.astype(I32),
        ae_req_n=p.ae_req_n.astype(I32),
        ae_req_commit=p.ae_req_commit.astype(I32),
        ae_rsp_t=stamp(p.ae_rsp_rel),
        ae_rsp_term=p.ae_rsp_term.astype(I32),
        ae_rsp_success=_unpack_bool_rows(p.ae_rsp_success_bits, n),
        ae_rsp_match=p.ae_rsp_match.astype(I32),
        sn_req_t=stamp(p.sn_req_rel),
        sn_req_term=p.sn_req_term.astype(I32),
        snap_installed_src=p.snap_installed_src.astype(I32),
        snap_installed_len=p.snap_installed_len.astype(I32),
        next_cmd=p.next_cmd.astype(I32),
        shadow_term=p.shadow_term.astype(I32),
        shadow_val=cmd(p.shadow_val),
        shadow_base=p.shadow_base.astype(I32),
        shadow_len=p.shadow_len.astype(I32),
        shadow_prefix_hash=p.shadow_prefix_hash,
        violations=p.violations,
        first_violation_tick=p.first_violation_tick.astype(I32),
        first_leader_tick=p.first_leader_tick.astype(I32),
        msg_count=p.msg_count,
        snap_install_count=p.snap_install_count,
        log_tick=p.log_tick.astype(I32),
        shadow_sub=p.shadow_sub.astype(I32),
        lat_hist=p.lat_hist.astype(I32),
        ev_counts=p.ev_counts.astype(I32),
        phase_hist=p.phase_hist.astype(I32),
        phase_ticks=p.phase_ticks,
        lat_ticks=p.lat_ticks,
        worst_lat=p.worst_lat.astype(I32),
        worst_phases=p.worst_phases.astype(I32),
        worst_key=p.worst_key,
        worst_client=p.worst_client,
        worst_sub=p.worst_sub.astype(I32),
    )


def packed_layout_reason(cfg: SimConfig, kn, ticks_needed: int) -> Optional[str]:
    """None when the packed schema is EXACT for a run of up to
    ``ticks_needed`` per-lane ticks under knob values ``kn`` — else a
    human-readable reason the engine falls back to the wide layout (and
    reports it as ``state_layout: "wide"``).

    ``kn`` must be concrete (every entry point builds knobs from Python
    values before compiling). The coverage pool's refill mutates only the
    [0, 1] probability knobs (coverage.MUTABLE_KNOBS), so a gate passed at
    entry cannot be invalidated by mutation mid-run.
    """
    if cfg.n_nodes > 16:
        return (
            f"n_nodes {cfg.n_nodes} > 16: role pairs (2 bits/node) and "
            "adjacency/vote row masks must fit one u32 word"
        )
    if cfg.ae_max > np.iinfo(np.uint8).max:
        return f"ae_max {cfg.ae_max} exceeds the u8 ae_req_n field"
    if ticks_needed > cfg.max_lane_ticks:
        return (
            f"run needs {ticks_needed} per-lane ticks > max_lane_ticks "
            f"{cfg.max_lane_ticks} (raise SimConfig.max_lane_ticks to pack "
            "longer horizons; widths re-derive automatically)"
        )
    k = jax.tree.map(np.asarray, kn)
    b = packed_bounds(cfg)
    if (k.delay_max > b.rel_stamp - 1).any():
        return (
            f"delay_max {k.delay_max} > {b.rel_stamp - 1}: mailbox stamps "
            "are stored tick-relative in one u8 (0 = empty)"
        )
    if (k.delay_min < 1).any():
        # a zero-delay send stamps the CURRENT tick, which the relative
        # encoding cannot distinguish from an empty slot (rel 0) — and the
        # pool entry points do not route through _validate_knobs, so the
        # exactness gate must reject it here
        return f"delay_min {k.delay_min} < 1: a same-tick stamp would " \
               "pack as an empty mailbox slot"
    if (k.eto_max + (cfg.n_nodes - 1) * k.eto_skew
            > np.iinfo(np.uint16).max).any():
        return (
            f"eto_max {k.eto_max} + (n-1) * eto_skew {k.eto_skew} exceeds "
            "the u16 timer field"
        )
    if (k.heartbeat_ticks > np.iinfo(np.uint16).max).any():
        return f"heartbeat_ticks {k.heartbeat_ticks} exceeds the u16 field"
    # gray-failure fields/draws (ISSUE 19): a limping node's stretched
    # delay must still fit the u8 relative stamp, the multiplier its u8
    # field, and a stall spike its u16 field
    if (k.limp_mult_max > np.iinfo(np.uint8).max).any():
        return f"limp_mult_max {k.limp_mult_max} exceeds the u8 limp field"
    if ((k.limp_mult_max > 1)
            & (k.delay_max * k.limp_mult_max > b.rel_stamp - 1)).any():
        return (
            f"delay_max {k.delay_max} * limp_mult_max {k.limp_mult_max} "
            f"> {b.rel_stamp - 1}: a limping node's stretched delay must "
            "fit the u8 tick-relative mailbox stamp"
        )
    if (k.fsync_stall_ticks > np.iinfo(np.uint16).max).any():
        return (
            f"fsync_stall_ticks {k.fsync_stall_ticks} exceeds the u16 "
            "fsync_stall field"
        )
    return None


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf — the live-buffer footprint
    measurement behind the ``state_hbm_bytes``/``bytes_per_lane`` summary
    telemetry (actual buffer sizes, never a schema estimate)."""
    return int(sum(x.nbytes for x in jax.tree.leaves(tree)))


def abstract_bytes(tree) -> int:
    """tree_bytes over a ``jax.eval_shape`` result: the byte total of the
    buffers a program WOULD carry (shape x itemsize — identical to the
    live-buffer number for dense arrays) without instantiating them. The
    service fuzz entry points use it to report their resident-carry
    footprint at build time instead of paying an extra device allocation."""
    return int(sum(
        int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(tree)
    ))


# Public aliases for the service-layer packed schemas (ISSUE 11): each
# service module derives its own field widths from config.packed_bounds
# through these, so the exact-or-wide derivation has one implementation.
uint_for = _uint_for
sint_for = _sint_for


def pack_fields(tree, dtypes: dict) -> dict:
    """The cast-only share of a service-layer pack: ``{field: narrow
    array}`` for every (name, dtype) entry — bool leaves pass through
    (already 1 byte), everything else downcasts to its derived dtype.
    Exact for in-bounds values by construction; the per-layer layout gate
    is what guarantees in-bounds."""
    out = {}
    for f, dt in dtypes.items():
        x = getattr(tree, f)
        out[f] = x if dt == BOOL else x.astype(dt)
    return out


def unpack_fields(tree, dtypes: dict) -> dict:
    """Exact inverse of pack_fields: widen every cast field back to the
    i32/bool dtypes the service tick runs on."""
    out = {}
    for f, dt in dtypes.items():
        x = getattr(tree, f)
        out[f] = x if dt == BOOL else x.astype(I32)
    return out
