"""The state-of-the-world of one simulated Raft cluster as a pytree of dense arrays.

One ``ClusterState`` holds every node's Raft state plus the in-flight network as
single-slot per-(dst, src) mailbox tensors. ``jax.vmap`` over a leading cluster axis
turns this into the batched fuzzer state (tens of thousands of independent clusters).

Design notes (vs the reference, SURVEY.md §2.6/§7):
- Persistent state (term, voted_for, log) *is* the array — the lockstep phase order
  (state updates happen before message emission within a tick) gives the
  persist-before-send ordering the reference gets from fsync-before-reply
  (/root/reference/src/raft/raft.rs:224-233). Crash keeps these arrays; restart only
  resets volatile fields (role, timers, votes, commit, next/match).
- DURABILITY is modeled separately from the arrays (the madsim ``fs`` axis:
  crash/restore with partially durable files): ``durable_len`` plus the
  ``durable_term``/``durable_voted_for`` shadows are the per-node fsync
  watermark — what has actually reached disk. The correct algorithm fsyncs
  before any state-exposing emission (reply/broadcast/append-at-leader,
  step.py) and every ``fsync_every`` ticks in the background; a crash with
  ``p_lose_unsynced`` rolls term/voted_for/log_len back to the watermark
  (the un-fsynced suffix is the page cache lost at power-off). Compaction
  and install-snapshot persist in the reference (raft.rs snapshot()/
  cond_install_snapshot), so ``base``/``snap_term``/``prefix_hash`` are
  durable by construction and need no shadows.
- The network is modeled like madsim's per-message loss/latency draws
  (/root/reference/src/raft/tester.rs:127-137): each directed (dst, src) pair has one
  slot per message type with a delivery tick; overwriting an undelivered slot models
  packet loss (counted faithfully as Raft must tolerate it).
- Log indices are 1-based as in Raft. The log array is a CANONICAL RING:
  absolute index ``a`` always lives in lane ``(a - 1) mod log_cap``, ``base`` is
  the snapshot boundary (indices 1..base are compacted away; the live window is
  ``(base, base + log_cap]``), and ``log_len`` / ``commit`` stay ABSOLUTE
  (highest index present / committed). ``snap_term`` is the term at index
  ``base``. Because an index's lane never changes, compaction and
  install-snapshot are pure ``base`` bumps — no data movement — and every
  access is a lane-vectorized one-hot select (per-row dynamic gathers/shifts
  serialize on TPU). This is what lets fuzz histories run far past ``log_cap``
  (SURVEY.md §5: "long histories → fixed-size buffers + on-device compaction").
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from madraft_tpu.tpusim.config import FOLLOWER, SimConfig

I32 = jnp.int32
BOOL = jnp.bool_


class ClusterState(NamedTuple):
    """All arrays for a single cluster (vmap adds the cluster axis)."""

    tick: jax.Array            # i32 scalar: current tick
    # --- per-node Raft state [N] ---
    term: jax.Array            # i32 current term (persistent)
    voted_for: jax.Array       # i32, -1 = none (persistent)
    role: jax.Array            # i32: 0 follower / 1 candidate / 2 leader
    timer: jax.Array           # i32 ticks until election timeout
    hb: jax.Array              # i32 ticks until next leader heartbeat
    alive: jax.Array           # bool
    # --- log window [N, CAP] (persistent; slot k = absolute index base+k+1) ---
    log_term: jax.Array        # i32
    log_val: jax.Array         # i32 (commands are unique ints)
    log_len: jax.Array         # i32 [N] absolute length (highest index present)
    base: jax.Array            # i32 [N] snapshot boundary (persistent)
    snap_term: jax.Array       # i32 [N] term at index `base` (persistent)
    prefix_hash: jax.Array     # i32 [N] order-free hash of entries 1..base
    #                            (persistent; folded at compaction, adopted at
    #                            install-snapshot) — lets the durability oracle
    #                            see divergence on entries older than the
    #                            window (step.py prefix-divergence check)
    commit: jax.Array          # i32 [N] committed count, absolute (volatile)
    # --- fsync watermark (what has reached disk; see module docstring) ---
    durable_len: jax.Array       # i32 [N] highest fsynced log index (absolute);
    #                              invariants: base <= durable_len <= log_len
    durable_term: jax.Array      # i32 [N] fsynced shadow of `term`
    durable_voted_for: jax.Array  # i32 [N] fsynced shadow of `voted_for`
    compact_floor: jax.Array   # i32 [N] service-layer cap on the compaction
    #                            boundary (= its apply cursor); unused when
    #                            cfg.compact_at_commit
    # --- candidate / leader bookkeeping ---
    votes: jax.Array           # bool [N, N]: votes[i, j] = candidate i holds j's grant
    next_idx: jax.Array        # i32 [N, N]: leader i's next index for peer j (1-based)
    match_idx: jax.Array       # i32 [N, N]: leader i's known match count for peer j
    # --- network ---
    adj: jax.Array             # bool [N, N] directed link usable (diag True)
    # RequestVote request mailbox [dst, src]
    rv_req_t: jax.Array        # i32 delivery tick; 0 = empty
    rv_req_term: jax.Array
    rv_req_lli: jax.Array      # candidate last log index (count)
    rv_req_llt: jax.Array      # candidate last log term
    # RequestVote response mailbox [dst(candidate), src(voter)]
    rv_rsp_t: jax.Array
    rv_rsp_term: jax.Array
    rv_rsp_granted: jax.Array  # bool
    # AppendEntries request mailbox [dst, src]
    ae_req_t: jax.Array
    ae_req_term: jax.Array
    ae_req_prev: jax.Array     # prev log index (count before batch)
    ae_req_prev_term: jax.Array
    ae_req_n: jax.Array        # entries carried (<= ae_max); the entry
    #                            payload itself is read from the sender's
    #                            live log at delivery (read-at-delivery, see
    #                            step.py AE delivery) — no entry mailboxes
    ae_req_commit: jax.Array   # leader commit
    # AppendEntries response mailbox [dst(leader), src(follower)]
    ae_rsp_t: jax.Array
    ae_rsp_term: jax.Array
    ae_rsp_success: jax.Array  # bool
    ae_rsp_match: jax.Array    # success: new match count; failure: next-index hint - 1
    # InstallSnapshot trigger mailbox [dst, src] (raft.rs:149-168). The payload
    # (boundary, snapshot term, service state) is read from the SENDER's live
    # snapshot at delivery — semantically the snapshot "sent at delivery
    # instant"; a dead sender at delivery = a lost message. The LEADER term
    # rides in the message (sn_req_term): like every RPC it deposes stale
    # leaders, and an install is only accepted from the current term's leader
    # — otherwise a deposed leader could truncate its fork and re-mint old
    # indices in its stale term, breaking log matching. Install outcome is
    # surfaced to service layers via snap_installed_src/len below.
    sn_req_t: jax.Array
    sn_req_term: jax.Array
    snap_installed_src: jax.Array  # i32 [N]: src installed from this tick (-1)
    snap_installed_len: jax.Array  # i32 [N]: boundary adopted this tick
    # --- workload / oracle ---
    next_cmd: jax.Array        # i32 scalar: per-cluster unique command counter
    # Committed-entry shadow (durability oracle) — windowed like the logs:
    # slot k = absolute index shadow_base+k+1; shadow_len is absolute.
    shadow_term: jax.Array     # i32 [CAP]
    shadow_val: jax.Array      # i32 [CAP]
    shadow_base: jax.Array     # i32 scalar
    shadow_len: jax.Array      # i32 scalar
    shadow_prefix_hash: jax.Array  # i32 scalar: hash of entries slid out of
    #                                the shadow window (same fold as nodes)
    violations: jax.Array      # i32 scalar sticky bitmask
    first_violation_tick: jax.Array  # i32 scalar, -1 = none
    first_leader_tick: jax.Array     # i32 scalar, -1 = none (liveness metric)
    msg_count: jax.Array       # i32 scalar: delivered messages (tester.rs:147-149)
    snap_install_count: jax.Array  # i32 scalar: snapshot installs (2D metric)


def durable_after_append(s: ClusterState, new_len: jax.Array) -> jax.Array:
    """Fsync watermark after a service-layer submit batch: submits model
    RaftHandle::start -> persist-at-append (raft.rs:311-313 — the leader's
    own log is commit-counted, so it must be durable), so the watermark
    follows the log where it grew. The single source of the rule for every
    service layer's submit path (kv/ctrler/shardkv)."""
    return jnp.where(new_len > s.log_len, new_len, s.durable_len)


def abstract_node_tuple(
    s: ClusterState, term_rank_levels: int, commit_delta_levels: int
) -> tuple:
    """The per-node abstract-state observation the coverage subsystem
    fingerprints (coverage.py, ROADMAP item 3) — defined here, next to the
    state it reads, so extending the abstraction means touching this tuple
    rather than the engine. Each component is quantized to a tiny static
    alphabet so the folded code space of a small cluster stays enumerable:

    - role:          0 follower / 1 candidate / 2 leader
    - alive:         0 / 1
    - term-rank:     #nodes with a strictly smaller term, clipped to
                     ``term_rank_levels - 1`` — captures WHO is ahead in the
                     term order, not by how much (absolute terms grow
                     without bound; their order pattern is what
                     distinguishes interleavings)
    - commit-delta:  ``commit - min(commit)`` clipped to
                     ``commit_delta_levels - 1`` — who lags the commit
                     frontier (the Figure-8 family lives in these lags)

    Returns four i32 ``[n]`` arrays (vmap adds the lane axis).
    """
    rank = jnp.clip(
        jnp.sum(s.term[None, :] < s.term[:, None], axis=1).astype(I32),
        0, term_rank_levels - 1,
    )
    delta = jnp.clip(s.commit - jnp.min(s.commit), 0, commit_delta_levels - 1)
    return s.role, s.alive.astype(I32), rank, delta


def init_cluster(cfg: SimConfig, key: jax.Array, kn=None) -> ClusterState:
    """Fresh cluster at tick 0 with randomized election timers (raft.rs:260-263).

    ``kn`` (a ``config.Knobs``) carries the dynamic knobs as traced scalars;
    omitted, they are baked from ``cfg`` as constants (single-config callers).
    """
    if kn is None:
        kn = cfg.knobs()
    n, cap = cfg.n_nodes, cfg.log_cap
    zn = jnp.zeros((n,), I32)
    znn = jnp.zeros((n, n), I32)
    timer = jax.random.randint(
        key, (n,), kn.eto_min, kn.eto_max + 1, dtype=I32
    )
    return ClusterState(
        tick=jnp.asarray(0, I32),
        term=zn,
        voted_for=jnp.full((n,), -1, I32),
        role=jnp.full((n,), FOLLOWER, I32),
        timer=timer,
        hb=zn,
        alive=jnp.ones((n,), BOOL),
        log_term=jnp.zeros((n, cap), I32),
        log_val=jnp.zeros((n, cap), I32),
        log_len=zn,
        base=zn,
        snap_term=zn,
        prefix_hash=zn,
        commit=zn,
        durable_len=zn,
        durable_term=zn,
        durable_voted_for=jnp.full((n,), -1, I32),
        compact_floor=zn,
        votes=jnp.zeros((n, n), BOOL),
        next_idx=jnp.ones((n, n), I32),
        match_idx=znn,
        adj=jnp.ones((n, n), BOOL),
        rv_req_t=znn, rv_req_term=znn, rv_req_lli=znn, rv_req_llt=znn,
        rv_rsp_t=znn, rv_rsp_term=znn, rv_rsp_granted=jnp.zeros((n, n), BOOL),
        ae_req_t=znn, ae_req_term=znn, ae_req_prev=znn, ae_req_prev_term=znn,
        ae_req_n=znn, ae_req_commit=znn,
        ae_rsp_t=znn, ae_rsp_term=znn,
        ae_rsp_success=jnp.zeros((n, n), BOOL), ae_rsp_match=znn,
        sn_req_t=znn,
        sn_req_term=znn,
        snap_installed_src=jnp.full((n,), -1, I32),
        snap_installed_len=zn,
        next_cmd=jnp.asarray(0, I32),
        shadow_term=jnp.zeros((cap,), I32),
        shadow_val=jnp.zeros((cap,), I32),
        shadow_base=jnp.asarray(0, I32),
        shadow_len=jnp.asarray(0, I32),
        shadow_prefix_hash=jnp.asarray(0, I32),
        violations=jnp.asarray(0, I32),
        first_violation_tick=jnp.asarray(-1, I32),
        first_leader_tick=jnp.asarray(-1, I32),
        msg_count=jnp.asarray(0, I32),
        snap_install_count=jnp.asarray(0, I32),
    )
