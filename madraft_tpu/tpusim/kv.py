"""Batched KV-service fuzzing on top of the Raft tick (Lab 3 on TPU).

This is the on-device analogue of the reference's kvraft layer and its test
oracles (SURVEY.md §4.2, /root/reference/src/kvraft/):

- Clerks are tensors: per cluster, ``n_clients`` clerks each hold one
  outstanding (client, seq, key) op at a time and bump seq only after the op
  committed — the ClerkCore contract (client.rs:32-63). A clerk whose op is
  not yet committed re-submits with some probability each tick, possibly to a
  *different* leader: that is exactly the duplicate-entry hazard the server's
  dup table must absorb (server.rs:68-70's "dedup retries").
- Each node runs an apply machine: an apply cursor chasing its commit index,
  a per-client dup table (last applied seq), and per-key rolling hashes of
  the applied append stream. Restart wipes the apply machine; it rebuilds by
  replaying the recovered log — the reference's restore-then-replay path.
- Oracles run as on-device reductions every tick:
    * exactly-once/order (VIOLATION_EXACTLY_ONCE): at apply, a client's seqs
      must arrive gap-free, and the number of applied ops must equal the
      highest applied seq (each op applied exactly once, in order) — the
      batched form of check_clnt_appends (tests.rs:21-43) and of the rsm
      seq-gap abort.
    * state-machine agreement (VIOLATION_KV_DIVERGE): two alive nodes whose
      apply cursors are equal must hold identical per-key hashes and counts
      (they applied the same committed prefix). This is the linearizability
      core the reference leaves commented out (tests.rs:386-390): commits are
      totally ordered by the log, so agreement on every applied prefix +
      exactly-once application is what a per-key history checker would
      verify.
- Deliberate bug modes validate the oracles: ``bug_skip_dedup`` applies
  duplicates (exactly-once must fire); ``bug_apply_uncommitted`` applies up
  to log_len instead of commit (agreement must fire).

The command stream reuses the raft log's i32 value channel: a KV op is packed
as (client, seq, key) — unique per op, never zero.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madraft_tpu.tpusim.config import (
    LEADER,
    NOOP_CMD,
    OPEN_QUEUE_SLOTS,
    SimConfig,
    metrics_dims,
    packed_bounds,
    zipf_map,
)
from madraft_tpu.tpusim.engine import (
    FuzzProgram,
    attach_layout_telemetry,
    choose_layout_from_reason,
)
from madraft_tpu.tpusim.metrics import (
    clerk_phase_matrix,
    fold_latencies,
    fold_latencies_by,
    fold_phases,
    update_worst,
)
from madraft_tpu.tpusim.state import (
    BOOL,
    ClusterState,
    I32,
    PackedClusterState,
    U8,
    durable_after_append,
    init_cluster,
    pack_fields,
    pack_state,
    packed_layout_reason,
    packed_spec_for,
    sint_for,
    uint_for,
    unpack_fields,
    unpack_state,
)
from madraft_tpu.tpusim.step import _lane_abs, _slot, step_cluster

# Additional violation bits (extending config.VIOLATION_*).
VIOLATION_EXACTLY_ONCE = 8   # duplicate or out-of-order apply of a client op
VIOLATION_KV_DIVERGE = 16    # equal apply cursors, different KV state
VIOLATION_STALE_READ = 32    # a Get observed a state outside its invoke..return
#                              linearization window (reads linearizability)

_SEQ_LIM = 1 << 15  # packing limit: seq fits 15 bits
# Op kinds — the reference's full Op set (msg.rs:3-8). Put REPLACES a key's
# value; on the count model a key's observable state is its MUTATION VERSION
# (appends + puts applied), which stays monotone, so the reads-linearizability
# interval oracle is exact with Puts in the mix (see KvState docstring).
_APPEND, _GET, _PUT = 0, 1, 2

# PRNG site ids, disjoint from step.py's _S_STEP_BLOCK (0).
_S_CLERK_START, _S_CLERK_TARGET, _S_CLERK_RETRY, _S_CLERK_KEY = 8, 9, 10, 11
_S_CLERK_KIND = 14
_S_CLERK_HINT = 15


@dataclasses.dataclass(frozen=True)
class KvConfig:
    """Knobs of the KV fuzzing layer. ``n_clients``/``n_keys``/``apply_max``
    shape the program; everything else (probabilities AND the bug injections)
    is dynamic — carried as traced scalars so every bug mode shares one
    compiled program with the correct service."""

    n_clients: int = 4
    n_keys: int = 4
    p_op: float = 0.3           # idle clerk starts a fresh op
    p_get: float = 0.3          # a fresh op is a Get with this probability,
    p_put: float = 0.0          # a Put with this one (one uniform draw:
    #                             u < p_get -> Get, u < p_get + p_put -> Put),
    #                             an Append otherwise — the reference's full
    #                             Op::{Get,Put,Append} set (msg.rs:3-8)
    p_retry: float = 0.5        # pending clerk re-submits this tick
    apply_max: int = 4          # apply-machine entries per node per tick
    # Oracle-validation bug modes (None/False = correct service).
    bug_skip_dedup: bool = False        # apply duplicates blindly
    bug_apply_uncommitted: bool = False  # apply past the commit index
    bug_stale_read: bool = False  # serve Gets from the contacted node's local
    #                               (possibly lagging) state at submit time —
    #                               the classic read-from-follower bug the
    #                               linearizability oracle must catch
    # NotLeader{hint} routing (the reference clerk follows leader hints,
    # /root/reference/src/kvraft/msg.rs:10-18, client.rs:32-63). 0.0 keeps
    # the historic random routing; with p > 0 a submitting clerk targets its
    # believed leader with probability p: a submit that reaches the leader
    # pins the belief, one that reaches an alive non-leader adopts that
    # node's hint (the leader of the node's own term — "whoever I heard
    # from"), and a dead target clears it.
    p_follow_hint: float = 0.0
    retry_wait: int = 0  # ticks a clerk pauses after its submit LANDED at an
    #                      alive leader before re-submitting — the ClerkCore
    #                      await-reply pacing (client.rs:56's 500 ms call
    #                      timeout). 0 keeps the historic fire-at-p_retry
    #                      model; without it, hint-following clerks spam the
    #                      leader with duplicate appends of the SAME op and
    #                      flow-control backpressure throttles the whole
    #                      cluster (measured: hints at 0.9 were ~0.6x random
    #                      — the model, not the protocol)
    bug_stale_hint: bool = False  # nodes hint the next FOLLOWER in the ring
    #                               instead of the leader — hint-following
    #                               clerks chase a leaderless cycle (the
    #                               deposed-leaders-hint-each-other loop);
    #                               caught as a measured liveness collapse
    #                               vs random routing (tests), not a safety
    #                               oracle: hints only steer routing
    # --- open-loop traffic shape (ISSUE 19; all dynamic knobs) ---
    open_rate: float = 0.0   # offered load: per-clerk per-tick arrival
    #                          probability (Bernoulli-per-tick ~ Poisson at
    #                          small rates). Arrivals queue regardless of
    #                          whether the clerk is busy — the OPEN-loop
    #                          regime where queues and tails blow up; the
    #                          submit stamp is the ARRIVAL tick, so queue
    #                          wait lands in lat_hist and the leader_wait
    #                          phase. Harvested from the free low 9 bits of
    #                          the p_op start word: zero extra PRNG draws.
    open_queue_cap: int = 0  # bounded pending queue per clerk (arrivals
    #                          past it DROP and are counted); 0 = the
    #                          historic closed-loop clerk, which is also
    #                          the neutral bit-identity value. Capped at
    #                          config.OPEN_QUEUE_SLOTS (the stamp ring).
    zipf_a: float = 1.0      # hot-key skew exponent on the fresh-op key
    #                          draw (config.zipf_map): 1.0 = the historic
    #                          uniform draw bit-identically; larger values
    #                          concentrate traffic on low-numbered keys,
    #                          feeding the per-key attribution axis

    def __post_init__(self):
        if self.p_get + self.p_put > 1.0:
            raise ValueError(
                f"p_get ({self.p_get}) + p_put ({self.p_put}) must stay <= 1 "
                "(one uniform draw splits Get/Put/Append; an over-unity pair "
                "would silently starve Appends)"
            )
        if not 0.0 <= self.open_rate <= 1.0:
            raise ValueError(f"open_rate {self.open_rate} not in [0, 1] "
                             "(per-tick arrival probability)")
        if not 0 <= self.open_queue_cap <= OPEN_QUEUE_SLOTS:
            raise ValueError(
                f"open_queue_cap {self.open_queue_cap} not in "
                f"[0, {OPEN_QUEUE_SLOTS}] (the arrival-stamp ring size)"
            )
        if self.zipf_a < 1.0:
            raise ValueError(f"zipf_a {self.zipf_a} must be >= 1.0 "
                             "(1.0 = uniform)")
        # every packed op must stay below NOOP_CMD (the leader no-op
        # sentinel) or a real client op would be skipped as a no-op forever
        # (silent clerk livelock) — and below i32
        top = _pack(self, self.n_clients - 1, _SEQ_LIM - 1, self.n_keys - 1, 3)
        if top >= NOOP_CMD:
            raise ValueError(
                f"n_clients ({self.n_clients}) x n_keys ({self.n_keys}) "
                f"overflow the op packing (max {top} >= NOOP_CMD {NOOP_CMD})"
            )

    def replace(self, **kw) -> "KvConfig":
        return dataclasses.replace(self, **kw)

    def knobs(self) -> "KvKnobs":
        return KvKnobs(
            p_op=jnp.float32(self.p_op),
            p_get=jnp.float32(self.p_get),
            p_put=jnp.float32(self.p_put),
            p_retry=jnp.float32(self.p_retry),
            p_follow_hint=jnp.float32(self.p_follow_hint),
            retry_wait=jnp.int32(self.retry_wait),
            bug_skip_dedup=jnp.bool_(self.bug_skip_dedup),
            bug_apply_uncommitted=jnp.bool_(self.bug_apply_uncommitted),
            bug_stale_read=jnp.bool_(self.bug_stale_read),
            bug_stale_hint=jnp.bool_(self.bug_stale_hint),
            open_rate=jnp.float32(self.open_rate),
            open_queue_cap=jnp.int32(self.open_queue_cap),
            zipf_a=jnp.float32(self.zipf_a),
        )

    def static_key(self) -> "KvConfig":
        return KvConfig(n_clients=self.n_clients, n_keys=self.n_keys,
                        apply_max=self.apply_max)


class KvKnobs(NamedTuple):
    """Dynamic KV-layer knobs (see KvConfig). Uniform scalars normally;
    ``make_kv_sweep_fn`` broadcasts them per cluster so heterogeneous
    workload mixes AND bug injections sweep across the batch in one
    program (engine.make_sweep_fn's design on the service layer)."""

    p_op: jax.Array
    p_get: jax.Array
    p_put: jax.Array
    p_retry: jax.Array
    p_follow_hint: jax.Array
    retry_wait: jax.Array
    bug_skip_dedup: jax.Array
    bug_apply_uncommitted: jax.Array
    bug_stale_read: jax.Array
    bug_stale_hint: jax.Array
    open_rate: jax.Array
    open_queue_cap: jax.Array
    zipf_a: jax.Array

    def broadcast(self, n_clusters: int) -> "KvKnobs":
        return KvKnobs(*(jnp.broadcast_to(x, (n_clusters,)) for x in self))


class KvState(NamedTuple):
    """Raft cluster state + the KV service layer (vmap adds the cluster axis)."""

    raft: ClusterState
    # --- clerks [NC] ---
    clerk_seq: jax.Array     # i32 last started seq (0 = none yet)
    clerk_out: jax.Array     # bool: op clerk_seq is still uncommitted
    clerk_key: jax.Array     # i32 key of the outstanding op
    clerk_kind: jax.Array    # i32 op kind: _APPEND, _GET, or _PUT
    clerk_acked: jax.Array   # i32 highest committed (acked) seq
    clerk_leader: jax.Array  # i32 believed leader node (-1 unknown) — the
    #                          reference ClerkCore's leader_ cache, fed by
    #                          NotLeader{hint} replies (client.rs:32-63)
    clerk_wait: jax.Array    # i32 await-reply countdown (see retry_wait)
    # --- open-loop arrival queue (ISSUE 19; frozen at the zero init in the
    # neutral closed-loop mode). Cursor arithmetic: pending = arr - srv,
    # the stamp ring is indexed mod OPEN_QUEUE_SLOTS, and open_queue_cap
    # <= OPEN_QUEUE_SLOTS (validated) keeps live stamps from colliding. ---
    open_arr: jax.Array      # i32 [NC] arrivals accepted into the queue
    open_srv: jax.Array      # i32 [NC] arrivals started (dequeued)
    open_drop: jax.Array     # i32 [NC] arrivals dropped at a full queue
    open_stamp: jax.Array    # i32 [NC, OPEN_QUEUE_SLOTS] arrival-tick ring
    #                          (metrics only; dequeue reads it as the
    #                          submit stamp so queue wait is measured)
    clerk_sub: jax.Array     # i32 [NC] submit stamp: tick the outstanding op
    #                          STARTED (ISSUE 10 metrics; zero-size with
    #                          cfg.metrics off). At ack, t - clerk_sub folds
    #                          into the raft state's lat_hist — the client-
    #                          experienced submit->ack latency, retries and
    #                          leader-hunting included
    # --- phase boundary stamps (ISSUE 12; zero-size with metrics off).
    # sub <= app <= cmt <= apl-or-cmt <= ack tick by construction, so the
    # consecutive differences are the exact phase decomposition
    # (config.LATENCY_PHASES) and telescope to the e2e latency. ---
    clerk_app: jax.Array     # i32 [NC] first tick a submit LANDED (appended
    #                          at a self-believed leader; 0 = not yet) —
    #                          closes the leader_wait phase
    clerk_cmt: jax.Array     # i32 [NC] first tick the op showed in the
    #                          committed shadow — closes replicate
    clerk_apl: jax.Array     # i32 [NC] first tick a Get's observation was
    #                          recorded by an apply machine — closes apply
    client_retries: jax.Array  # i32 [NC] submit attempts (the per-client
    #                            event row: NotLeader hunts show up here)
    # --- attribution axes (ISSUE 12; zero-size with metrics off): e2e
    # latency histograms per key and per client, merged by plain addition
    # like every other hist row ---
    key_lat_hist: jax.Array     # i32 [NK, HIST_BUCKETS]
    client_lat_hist: jax.Array  # i32 [NC, HIST_BUCKETS]
    # --- reads-linearizability oracle state ---
    # The log totally orders mutations (Appends and Puts), so key k's
    # observable state IS its committed MUTATION VERSION — the count of
    # mutations applied, which is monotone even though a Put resets the
    # value string. A Get is linearizable iff its observed version lies in
    # [truth at invoke, truth at return]. This interval check is exact for
    # this datatype: for non-overlapping reads r1 < r2, obs(r2) >=
    # truth(invoke r2) >= truth(return r1) >= obs(r1), i.e. monotonicity
    # follows. It is the batched, closed-form analogue of the Wing-Gong
    # checker the C++ backend runs (cpp/kvraft/linearize.h; the reference
    # leaves those tests commented out, kvraft/tests.rs:386-390); the bridge
    # translates a version back to the concrete value string (last Put's
    # token + Appends after it) when exporting histories.
    truth_count: jax.Array   # i32 [NK] committed mutations per key
    #                          (shadow-derived, DEDUPED: clerk retries commit
    #                          duplicate entries; state counts each op once,
    #                          so truth must too)
    truth_max_seq: jax.Array  # i32 [NC] highest seq seen in the shadow per client
    clerk_get_lo: jax.Array  # i32 [NC] truth_count[key] captured at invoke
    clerk_get_obs: jax.Array  # i32 [NC] observed count; -1 = no reply yet
    clerk_last_obs: jax.Array  # i32 [NC] observation of the last COMPLETED Get
    #                            (stable across the reset at the next start —
    #                            what history exporters read; bridge.py)
    gets_done: jax.Array     # i32 [NC] completed Gets (workload metric)
    # --- per-node apply machines. The live set is volatile (crash resets to
    # the snapshot); the snap_* set is the persisted service snapshot at the
    # node's log base (the reference's "snapshot" file: dup table + state,
    # rsm.h save_snapshot), captured at compaction and shipped by
    # install-snapshot.
    applied: jax.Array       # i32 [N] apply cursor, absolute (>= base)
    last_seq: jax.Array      # i32 [N, NC] dup table: last applied seq
    apply_count: jax.Array   # i32 [N, NC] ops applied (must equal last_seq)
    key_hash: jax.Array      # i32 [N, NK] rolling hash of applied mutations
    key_count: jax.Array     # i32 [N, NK] applied mutation version per key
    snap_last_seq: jax.Array     # i32 [N, NC] (persistent)
    snap_apply_count: jax.Array  # i32 [N, NC] (persistent)
    snap_key_hash: jax.Array     # i32 [N, NK] (persistent)
    snap_key_count: jax.Array    # i32 [N, NK] (persistent)


def _check_kv_cfg(cfg: SimConfig) -> None:
    assert cfg.p_client_cmd == 0.0, "KV layer owns command injection"
    assert not cfg.compact_at_commit, (
        "KV fuzzing needs cfg.compact_at_commit=False: the compaction "
        "boundary must follow the apply cursor, not the commit index"
    )


def _pack(cfg: KvConfig, client, seq, key, kind):
    return (((client * _SEQ_LIM + seq) * cfg.n_keys + key) * 4 + kind) + 1


def _unpack(cfg: KvConfig, val):
    v = val - 1
    kind = v % 4
    v = v // 4
    key = v % cfg.n_keys
    cs = v // cfg.n_keys
    return cs // _SEQ_LIM, cs % _SEQ_LIM, key, kind  # client, seq, key, kind


def init_kv_cluster(
    cfg: SimConfig, kcfg: KvConfig, key: jax.Array, kn=None
) -> KvState:
    n, nc, nk = cfg.n_nodes, kcfg.n_clients, kcfg.n_keys
    return KvState(
        raft=init_cluster(cfg, key, kn),
        clerk_seq=jnp.zeros((nc,), I32),
        clerk_out=jnp.zeros((nc,), jnp.bool_),
        clerk_key=jnp.zeros((nc,), I32),
        clerk_kind=jnp.zeros((nc,), I32),
        clerk_acked=jnp.zeros((nc,), I32),
        clerk_leader=jnp.full((nc,), -1, I32),
        clerk_wait=jnp.zeros((nc,), I32),
        open_arr=jnp.zeros((nc,), I32),
        open_srv=jnp.zeros((nc,), I32),
        open_drop=jnp.zeros((nc,), I32),
        open_stamp=jnp.zeros((nc if cfg.metrics else 0, OPEN_QUEUE_SLOTS),
                             I32),
        clerk_sub=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_app=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_cmt=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_apl=jnp.zeros((nc if cfg.metrics else 0,), I32),
        client_retries=jnp.zeros((nc if cfg.metrics else 0,), I32),
        key_lat_hist=jnp.zeros((nk if cfg.metrics else 0,
                                metrics_dims(cfg)[0]), I32),
        client_lat_hist=jnp.zeros((nc if cfg.metrics else 0,
                                   metrics_dims(cfg)[0]), I32),
        truth_count=jnp.zeros((nk,), I32),
        truth_max_seq=jnp.zeros((nc,), I32),
        clerk_get_lo=jnp.zeros((nc,), I32),
        clerk_get_obs=jnp.full((nc,), -1, I32),
        clerk_last_obs=jnp.full((nc,), -1, I32),
        gets_done=jnp.zeros((nc,), I32),
        applied=jnp.zeros((n,), I32),
        last_seq=jnp.zeros((n, nc), I32),
        apply_count=jnp.zeros((n, nc), I32),
        key_hash=jnp.zeros((n, nk), I32),
        key_count=jnp.zeros((n, nk), I32),
        snap_last_seq=jnp.zeros((n, nc), I32),
        snap_apply_count=jnp.zeros((n, nc), I32),
        snap_key_hash=jnp.zeros((n, nk), I32),
        snap_key_count=jnp.zeros((n, nk), I32),
    )


def kv_step(
    cfg: SimConfig, kcfg: KvConfig, ks: KvState, cluster_key: jax.Array,
    kn=None, kkn=None,
) -> KvState:
    """One lockstep tick: raft tick, then apply machines, oracles, clerks."""
    if kn is None:
        _check_kv_cfg(cfg)
        kn = cfg.knobs()
    if kkn is None:
        kkn = kcfg.knobs()
    pre = ks.raft
    s = step_cluster(cfg, pre, cluster_key, kn)
    return _kv_service_tick(
        cfg, kcfg, ks, pre.alive, pre.base, pre.shadow_len, s, cluster_key,
        kn, kkn,
    )


def _kv_service_tick(
    cfg: SimConfig, kcfg: KvConfig, ks: KvState,
    pre_alive: jax.Array, pre_base: jax.Array, pre_shadow_len: jax.Array,
    s: ClusterState, cluster_key: jax.Array, kn, kkn,
) -> KvState:
    """The service share of one tick — apply machines, oracles, clerks —
    given the STEPPED raft state ``s`` and the three pre-tick raft views it
    needs (alive/base/shadow_len). ONE copy of the math for the wide step
    and the fused packed step (kv_step_packed): the fused path feeds it a
    widened VIEW of the packed carry, so packed-vs-wide bit-identity is a
    property of pack/unpack exactness, never of a parallel implementation."""
    n, cap, nc = cfg.n_nodes, cfg.log_cap, kcfg.n_clients
    me = jnp.arange(n, dtype=I32)
    t = s.tick
    key = jax.random.fold_in(cluster_key, t)
    nk = kcfg.n_keys

    # Committed truth per key (reads-linearizability ground truth): count the
    # appends newly recorded in the commit shadow this tick, DEDUPED the same
    # way the apply machines dedup — clerk retries put the same op at several
    # log positions, but the state applies it once. An entry is first-occurrence
    # iff its seq exceeds the client's max seq already seen (clerks serialize
    # seqs, so cross-tick duplicates always carry a stale seq) and no earlier
    # new lane this tick holds the same op. The shadow is the total order; an
    # entry that slides past the window in a single tick escapes the count,
    # matching the shadow oracle's own window caveat.
    sh_abs_now = _lane_abs(s.shadow_base, cap)  # [cap]
    sh_client, sh_seq, sh_key, sh_kind = _unpack(kcfg, s.shadow_val)
    sh_client = jnp.clip(sh_client, 0, nc - 1)
    sh_new = (
        (sh_abs_now > pre_shadow_len) & (sh_abs_now <= s.shadow_len)
        & (s.shadow_val != NOOP_CMD)  # leader no-ops are not client ops
    )
    cl_oh_sh = sh_client[:, None] == jnp.arange(nc, dtype=I32)[None, :]  # [cap, nc]
    prev_max_at = jnp.sum(
        jnp.where(cl_oh_sh, ks.truth_max_seq[None, :], 0), axis=1
    )  # [cap]: truth_max_seq[client of lane]
    dup_earlier = jnp.any(
        sh_new[None, :]
        & (sh_abs_now[None, :] < sh_abs_now[:, None])
        & (s.shadow_val[None, :] == s.shadow_val[:, None]),
        axis=1,
    )  # [cap]: an earlier new lane holds the same op
    sh_first = sh_new & (sh_seq > prev_max_at) & ~dup_earlier
    truth_count = ks.truth_count + jnp.sum(
        (sh_first & (sh_kind != _GET))[None, :]  # Appends AND Puts mutate
        & (sh_key[None, :] == jnp.arange(nk, dtype=I32)[:, None]),
        axis=1, dtype=I32,
    )
    truth_max_seq = jnp.maximum(
        ks.truth_max_seq,
        jnp.max(jnp.where(sh_new[:, None] & cl_oh_sh, sh_seq[:, None], 0), axis=0),
    )

    applied = ks.applied
    last_seq, apply_count = ks.last_seq, ks.apply_count
    key_hash, key_count = ks.key_hash, ks.key_count
    snap_last_seq, snap_apply_count = ks.snap_last_seq, ks.snap_apply_count
    snap_key_hash, snap_key_count = ks.snap_key_hash, ks.snap_key_count

    # 1. Crash/restart: the live apply machine resets to the node's own
    #    persisted snapshot; log replay from base rebuilds the rest
    #    (restore() + apply-channel replay, raft.rs:194-211).
    fresh = (~pre_alive & s.alive) | ~s.alive
    applied = jnp.where(fresh, s.base, applied)
    last_seq = jnp.where(fresh[:, None], snap_last_seq, last_seq)
    apply_count = jnp.where(fresh[:, None], snap_apply_count, apply_count)
    key_hash = jnp.where(fresh[:, None], snap_key_hash, key_hash)
    key_count = jnp.where(fresh[:, None], snap_key_count, key_count)

    # 2. Compaction this tick (base advanced, no install): the boundary is the
    #    pre-tick apply cursor (compact_floor), so the live tables BEFORE this
    #    tick's apply loop are exactly the state at the new base — capture
    #    them as the persisted snapshot (rsm.h maybe_snapshot).
    inst = s.snap_installed_src >= 0
    comp = (s.base != pre_base) & ~inst & s.alive
    snap_last_seq = jnp.where(comp[:, None], last_seq, snap_last_seq)
    snap_apply_count = jnp.where(comp[:, None], apply_count, snap_apply_count)
    snap_key_hash = jnp.where(comp[:, None], key_hash, snap_key_hash)
    snap_key_count = jnp.where(comp[:, None], key_count, snap_key_count)

    # 3. Install-snapshot this tick: adopt the sender's persisted snapshot
    #    (its pre-tick snap tables match the pre-tick base the trigger
    #    carried) as both live and persisted state; jump the cursor.
    #    One-hot over the (tiny) node axis instead of a dynamic row gather.
    src_oh = (me[None, :] == s.snap_installed_src[:, None])[:, :, None]  # [dst, src, 1]

    def _adopt(snap):
        return jnp.sum(jnp.where(src_oh, snap[None, :, :], 0), axis=1)

    ad_last_seq, ad_apply_count = _adopt(ks.snap_last_seq), _adopt(ks.snap_apply_count)
    ad_key_hash, ad_key_count = _adopt(ks.snap_key_hash), _adopt(ks.snap_key_count)
    applied = jnp.where(inst, s.base, applied)
    last_seq = jnp.where(inst[:, None], ad_last_seq, last_seq)
    apply_count = jnp.where(inst[:, None], ad_apply_count, apply_count)
    key_hash = jnp.where(inst[:, None], ad_key_hash, key_hash)
    key_count = jnp.where(inst[:, None], ad_key_count, key_count)
    snap_last_seq = jnp.where(inst[:, None], ad_last_seq, snap_last_seq)
    snap_apply_count = jnp.where(inst[:, None], ad_apply_count, snap_apply_count)
    snap_key_hash = jnp.where(inst[:, None], ad_key_hash, snap_key_hash)
    snap_key_count = jnp.where(inst[:, None], ad_key_count, snap_key_count)

    # ---------------------------------------------------------- apply machines
    # All row-indexed reads/writes are one-hot mask-reduces over the (tiny)
    # lane axes — dynamic per-row gathers/scatters serialize on TPU.
    viol = jnp.asarray(0, I32)
    limit = jnp.where(kkn.bug_apply_uncommitted, s.log_len, s.commit)
    lane = jnp.arange(cap, dtype=I32)[None, :]
    cl_lane = jnp.arange(nc, dtype=I32)[None, :]
    k_lane = jnp.arange(kcfg.n_keys, dtype=I32)[None, :]
    clerk_get_obs = ks.clerk_get_obs
    cl_ids = jnp.arange(nc, dtype=I32)
    for _ in range(kcfg.apply_max):
        can = s.alive & (applied < limit)
        pos = _slot(applied + 1, cap)  # canonical ring lane of index applied+1
        val = jnp.sum(jnp.where(lane == pos[:, None], s.log_val, 0), axis=-1)
        client, seq, k, kind = _unpack(kcfg, val)
        client = jnp.clip(client, 0, nc - 1)
        # a leader no-op is consumed (cursor advances) but is no client op
        is_op = can & (val != NOOP_CMD)
        cl_oh = cl_lane == client[:, None]            # [n, nc]
        prev = jnp.sum(jnp.where(cl_oh, last_seq, 0), axis=-1)
        dup = seq <= prev
        # order oracle: a first-time seq must be exactly prev+1 (the clerk
        # starts s+1 only after s committed, so committed order is gap-free).
        # bug_stale_read serves Gets outside the log, so gaps are legitimate
        # there and the gap-based checks stand down.
        viol |= jnp.where(
            ~kkn.bug_stale_read & jnp.any(is_op & ~dup & (seq > prev + 1)),
            VIOLATION_EXACTLY_ONCE, 0)
        do = is_op & (kkn.bug_skip_dedup | ~dup)
        # Gets read; Appends and Puts mutate the key state. The packed val
        # rides into the hash, so a put and an append at the same version
        # hash differently (kind is in the low bits).
        mut = do & (kind != _GET)
        k_oh = (k_lane == k[:, None]) & mut[:, None]  # [n, nk]
        key_hash = jnp.where(k_oh, key_hash * 1000003 + val[:, None], key_hash)
        key_count = jnp.where(k_oh, key_count + 1, key_count)
        apply_count = jnp.where(cl_oh & do[:, None], apply_count + 1, apply_count)
        last_seq = jnp.where(
            cl_oh & is_op[:, None], jnp.maximum(prev, seq)[:, None], last_seq
        )
        # Get observation: the value a Get returns is the key's mutation
        # version at its log position — a pure function of the log prefix, so
        # the first node to apply it yields the canonical reply (agreement
        # between apply machines is checked separately by KV_DIVERGE).
        obs_node = jnp.sum(
            jnp.where(k_lane == k[:, None], key_count, 0), axis=-1
        )  # [n]
        get_apply = do & (kind == _GET)
        m = (
            get_apply[None, :]
            & (client[None, :] == cl_ids[:, None])
            & (seq[None, :] == ks.clerk_seq[:, None])
        )  # [nc, n]
        cand = jnp.max(jnp.where(m, obs_node[None, :], -1), axis=1)
        clerk_get_obs = jnp.where(
            (clerk_get_obs < 0) & (cand >= 0), cand, clerk_get_obs
        )
        applied = jnp.where(can, applied + 1, applied)

    # exactly-once: ops applied per client == highest seq applied
    viol |= jnp.where(
        ~kkn.bug_stale_read
        & jnp.any(s.alive[:, None] & (apply_count != last_seq)),
        VIOLATION_EXACTLY_ONCE, 0)

    # state-machine agreement: equal cursors => identical applied state
    same_cursor = (
        (applied[:, None] == applied[None, :])
        & (applied[:, None] > 0)
        & s.alive[:, None] & s.alive[None, :]
    )
    hash_eq = jnp.all(
        (key_hash[:, None, :] == key_hash[None, :, :])
        & (key_count[:, None, :] == key_count[None, :, :]),
        axis=2,
    )
    viol |= jnp.where(jnp.any(same_cursor & ~hash_eq), VIOLATION_KV_DIVERGE, 0)

    # ------------------------------------------------------------------ clerks
    # ack: an outstanding op is acked once it appears in the committed shadow
    # log (ground truth of commits — the clerk's Ok reply); a Get additionally
    # needs its observation (recorded at first apply). The shadow is a window;
    # a clerk polls every tick, far faster than the window slides.
    key_lane = jnp.arange(nk, dtype=I32)[None, :]
    truth_at = jnp.sum(
        jnp.where(key_lane == ks.clerk_key[:, None], truth_count[None, :], 0),
        axis=1,
    )  # [nc]: committed-append truth for each clerk's key, as of now
    want = _pack(kcfg, cl_ids, ks.clerk_seq, ks.clerk_key, ks.clerk_kind)
    sh_live = _lane_abs(s.shadow_base, cap) <= s.shadow_len  # canonical ring
    in_shadow = jnp.any(
        (s.shadow_val[None, :] == want[:, None]) & sh_live[None, :], axis=1
    )
    is_get = ks.clerk_kind == _GET
    # phase boundary stamps (ISSUE 12): commit = first tick the op shows in
    # the shadow, apply = first tick its Get observation landed — recorded
    # while outstanding, reset at the next start
    clerk_cmt, clerk_apl = ks.clerk_cmt, ks.clerk_apl
    if cfg.metrics:
        clerk_cmt = jnp.where(
            ks.clerk_out & in_shadow & (clerk_cmt == 0), t, clerk_cmt
        )
        clerk_apl = jnp.where(
            ks.clerk_out & (clerk_get_obs >= 0) & (clerk_apl == 0), t,
            clerk_apl,
        )
    newly_acked = ks.clerk_out & in_shadow & (~is_get | (clerk_get_obs >= 0))
    # Reads linearizability: the observed count must lie in the op's
    # [invoke, return] truth window (exact for append-count registers; see
    # the KvState docstring).
    done_get = newly_acked & is_get
    viol |= jnp.where(
        jnp.any(
            done_get
            & ((clerk_get_obs < ks.clerk_get_lo) | (clerk_get_obs > truth_at))
        ),
        VIOLATION_STALE_READ, 0,
    )
    clerk_acked = jnp.where(newly_acked, ks.clerk_seq, ks.clerk_acked)
    clerk_out = ks.clerk_out & ~newly_acked
    gets_done = ks.gets_done + done_get.astype(I32)
    clerk_last_obs = jnp.where(done_get, clerk_get_obs, ks.clerk_last_obs)
    # metrics (ISSUE 10): the ack is the clerk's Ok reply — fold the op's
    # whole submit->ack latency (stamped at op START, so retries and
    # NotLeader hunting are inside the measured window, exactly what a
    # client experiences) into the cluster's latency histogram; the
    # attribution plane (ISSUE 12) additionally folds the phase
    # decomposition, the per-key/per-client axes, and the worst-op register
    lat_hist = s.lat_hist
    phase_hist, phase_ticks, lat_ticks = (
        s.phase_hist, s.phase_ticks, s.lat_ticks
    )
    worst = (s.worst_lat, s.worst_phases, s.worst_key, s.worst_client,
             s.worst_sub)
    key_lat_hist, client_lat_hist = ks.key_lat_hist, ks.client_lat_hist
    if cfg.metrics:
        e2e = t - ks.clerk_sub
        lat_hist = fold_latencies(lat_hist, e2e, newly_acked)
        ph = clerk_phase_matrix(
            t, ks.clerk_sub, ks.clerk_app, clerk_cmt, clerk_apl, is_get
        )
        phase_hist, phase_ticks, lat_ticks = fold_phases(
            phase_hist, phase_ticks, lat_ticks, ph, e2e, newly_acked
        )
        worst = update_worst(
            worst, e2e, newly_acked, ph, ks.clerk_key, cl_ids, ks.clerk_sub
        )
        key_lat_hist = fold_latencies_by(
            key_lat_hist, e2e, newly_acked, ks.clerk_key
        )
        client_lat_hist = fold_latencies_by(
            client_lat_hist, e2e, newly_acked, cl_ids
        )

    # start fresh ops / retry pending ones. The p_op start word is drawn at
    # BIT level: the uniform below reconstructs jax.random.uniform's
    # mantissa path bit-identically (top 23 bits), which frees the low
    # 9 bits as the open-loop arrival draw (ISSUE 19) — the gray traffic
    # shape costs ZERO extra PRNG draws and the closed-loop start decision
    # is unchanged to the bit.
    kk = jax.random.split(jax.random.fold_in(key, _S_CLERK_START), 4)
    w_start = jax.random.bits(kk[0], (nc,))
    u_start = jax.lax.bitcast_convert_type(
        (w_start >> np.uint32(9)) | np.uint32(0x3F800000), jnp.float32
    ) - 1.0
    # open-loop arrivals: offered load lands in a bounded per-clerk queue
    # whether or not the clerk is busy; past the cap it drops (and counts)
    openloop = kkn.open_queue_cap > 0
    arrive = openloop & (
        (w_start & np.uint32(0x1FF)).astype(jnp.float32)
        * jnp.float32(2.0 ** -9)
        < kkn.open_rate
    )
    drop = arrive & (ks.open_arr - ks.open_srv >= kkn.open_queue_cap)
    enq = arrive & ~drop
    open_arr = ks.open_arr + enq.astype(I32)
    open_drop = ks.open_drop + drop.astype(I32)
    open_stamp = ks.open_stamp
    if cfg.metrics:
        slot_e = (
            jnp.arange(OPEN_QUEUE_SLOTS, dtype=I32)[None, :]
            == (ks.open_arr % OPEN_QUEUE_SLOTS)[:, None]
        )
        open_stamp = jnp.where(enq[:, None] & slot_e, t, ks.open_stamp)
    start = (
        ~clerk_out
        & jnp.where(openloop, open_arr > ks.open_srv, u_start < kkn.p_op)
        & (ks.clerk_seq < _SEQ_LIM - 1)
    )
    open_srv = ks.open_srv + (openloop & start).astype(I32)
    clerk_seq = jnp.where(start, ks.clerk_seq + 1, ks.clerk_seq)
    # hot-key skew: zipf_map is the identity at zipf_a=1.0 (the randint
    # draw itself is unchanged either way — same draw count, same bits)
    clerk_key = jnp.where(
        start,
        zipf_map(
            jax.random.randint(kk[1], (nc,), 0, kcfg.n_keys, dtype=I32),
            kcfg.n_keys, kkn.zipf_a,
        ),
        ks.clerk_key,
    )
    u_kind = jax.random.uniform(jax.random.fold_in(key, _S_CLERK_KIND), (nc,))
    clerk_kind = jnp.where(
        start,
        jnp.where(
            u_kind < kkn.p_get,
            _GET,
            jnp.where(u_kind < kkn.p_get + kkn.p_put, _PUT, _APPEND),
        ),
        ks.clerk_kind,
    )
    # a fresh Get captures its invoke-time truth; its observation resets
    truth_at_new = jnp.sum(
        jnp.where(key_lane == clerk_key[:, None], truth_count[None, :], 0),
        axis=1,
    )
    clerk_get_lo = jnp.where(start, truth_at_new, ks.clerk_get_lo)
    clerk_get_obs = jnp.where(start, -1, clerk_get_obs)
    clerk_sub = ks.clerk_sub
    clerk_app = ks.clerk_app
    if cfg.metrics:
        # submit stamp: a fresh op's latency window opens NOW — except in
        # the open-loop regime, where it opens at the op's ARRIVAL tick
        # (read from the stamp ring at the dequeue cursor; a same-tick
        # arrive->start reads the stamp just written, i.e. t), so the queue
        # wait is inside the measured window and lands in the leader_wait
        # phase. (An op never acks in its start tick — the serve path below
        # requires ~start and the shadow ack needs a commit.) The phase
        # boundary stamps reset with it.
        slot_d = (
            jnp.arange(OPEN_QUEUE_SLOTS, dtype=I32)[None, :]
            == (ks.open_srv % OPEN_QUEUE_SLOTS)[:, None]
        )
        arr_t = jnp.sum(jnp.where(slot_d, open_stamp, 0), axis=1)
        clerk_sub = jnp.where(start, jnp.where(openloop, arr_t, t),
                              clerk_sub)
        clerk_app = jnp.where(start, 0, clerk_app)
        clerk_cmt = jnp.where(start, 0, clerk_cmt)
        clerk_apl = jnp.where(start, 0, clerk_apl)
    clerk_out = clerk_out | start
    retry = clerk_out & (
        start
        | (
            jax.random.bernoulli(kk[2], kkn.p_retry, (nc,))
            & (ks.clerk_wait <= 0)
        )
    )
    client_retries = ks.client_retries
    if cfg.metrics:
        # per-client submit-attempt counter (the event row of the
        # per-client axis): every attempt counts, whether it lands, is
        # bug-served, or bounces off a non-leader
        client_retries = client_retries + retry.astype(I32)
    target = jax.random.randint(kk[3], (nc,), 0, n, dtype=I32)
    # NotLeader{hint} routing (msg.rs:10-18): with p_follow_hint, a clerk
    # holding a leader belief targets it instead of the random draw.
    kk_h = jax.random.split(jax.random.fold_in(key, _S_CLERK_HINT))
    follow = (
        jax.random.bernoulli(kk_h[0], kkn.p_follow_hint, (nc,))
        & (ks.clerk_leader >= 0)
    )
    target = jnp.where(follow, jnp.clip(ks.clerk_leader, 0, n - 1), target)

    # Bug mode (dynamic knob; a no-op mask when off): the contacted node —
    # leader or not — serves the Get immediately from its own (possibly
    # lagging) applied state, skipping the log. The classic read-from-follower
    # bug; the linearizability oracle must flag any observation below the
    # invoke-time truth.
    tgt_oh = me[None, :] == target[:, None]  # [nc, n]
    local_cnt = jnp.sum(
        jnp.where(
            tgt_oh[:, :, None]
            & (jnp.arange(nk, dtype=I32)[None, None, :]
               == clerk_key[:, None, None]),
            key_count[None, :, :], 0,
        ),
        axis=(1, 2),
    )  # [nc]: key_count[target_c, key_c]
    # ~start: the serve "RPC" takes at least a tick, so an op never
    # completes in its start tick — this also keeps completions of
    # consecutive ops on distinct ticks, which the history exporter's
    # per-tick clerk_last_obs snapshot relies on (bridge.py)
    served = (
        kkn.bug_stale_read
        & retry & ~start
        & (clerk_kind == _GET)
        & jnp.any(tgt_oh & s.alive[None, :], axis=1)
    )
    # upper bound = truth at serve time — identical to truth_at_new above
    # (same clerk_key, same truth_count; nothing commits in between)
    viol |= jnp.where(
        jnp.any(
            served
            & ((local_cnt < clerk_get_lo) | (local_cnt > truth_at_new))
        ),
        VIOLATION_STALE_READ, 0,
    )
    clerk_acked = jnp.where(served, clerk_seq, clerk_acked)
    clerk_out = clerk_out & ~served
    gets_done = gets_done + served.astype(I32)
    retry = retry & ~served
    # record the served value so history exporters (bridge) can see it
    clerk_last_obs = jnp.where(served, local_cnt, clerk_last_obs)
    if cfg.metrics:
        # the bug-mode local serve is an ack too (served ops are ~start, so
        # their stamp is untouched by this tick's start update above). A
        # local serve skips the log entirely, so its whole latency is
        # attributed to the apply phase (state was read from an apply
        # machine) — any consecutive split keeps the phase sum exact.
        e2e_s = t - clerk_sub
        lat_hist = fold_latencies(lat_hist, e2e_s, served)
        zeros = jnp.zeros_like(e2e_s)
        ph_s = jnp.stack([zeros, zeros, e2e_s, zeros])
        phase_hist, phase_ticks, lat_ticks = fold_phases(
            phase_hist, phase_ticks, lat_ticks, ph_s, e2e_s, served
        )
        worst = update_worst(
            worst, e2e_s, served, ph_s, clerk_key, cl_ids, clerk_sub
        )
        key_lat_hist = fold_latencies_by(key_lat_hist, e2e_s, served,
                                         clerk_key)
        client_lat_hist = fold_latencies_by(client_lat_hist, e2e_s, served,
                                            cl_ids)

    violations = s.violations | viol
    first_violation_tick = jnp.where(
        (s.first_violation_tick < 0) & (viol != 0), t, s.first_violation_tick
    )

    # submit: append at the targeted node iff it believes it is the leader
    # (RaftHandle::start, raft.rs:131; a stale leader accepts and the entry
    # is later overwritten — the rejoin_2b scenario). Gets ride the log too:
    # the committed-read path (the reference commits Get ops for exactly this
    # linearizability, kvraft/server.rs Op::Get).
    log_term, log_val, log_len = s.log_term, s.log_val, s.log_len
    landed = []
    for c in range(nc):
        sel = me == target[c]                         # one-hot over nodes
        ok = (
            sel
            & retry[c]
            & s.alive
            & (s.role == LEADER)
            & (log_len - s.base < cap)  # window has room
            & (log_len - s.commit < kn.flow_cap)  # proposal backpressure
        )
        v = _pack(kcfg, jnp.asarray(c, I32), clerk_seq[c], clerk_key[c],
                  clerk_kind[c])
        hit = ok[:, None] & (lane == _slot(log_len + 1, cap)[:, None])
        log_term = jnp.where(hit, s.term[:, None], log_term)
        log_val = jnp.where(hit, v, log_val)
        log_len = jnp.where(ok, log_len + 1, log_len)
        landed.append(jnp.any(ok))
    if cfg.metrics:
        # the leader_wait boundary: the FIRST tick this op's submit was
        # accepted by a self-believed leader (a stale leader counts — the
        # hunt is over even if replication then restarts; the extra wait
        # lands in the replicate phase, where the re-replication happened)
        clerk_app = jnp.where(
            jnp.stack(landed) & clerk_out & (clerk_app == 0), t, clerk_app
        )

    # The submit's "reply" teaches the clerk where the leader is (ClerkCore
    # leader_ cache, client.rs:32-63): reaching the leader pins the belief;
    # an alive non-leader answers NotLeader{hint} — its hint is the leader
    # of its OWN term ("whoever I heard from"; -1 if it knows none); a dead
    # target times out and the belief clears. Under bug_stale_hint nodes
    # hint the next FOLLOWER in the ring — skipping the real leader — so
    # hint-followers chase a leaderless cycle (hints steer routing only;
    # the failure mode is measured liveness collapse, tests).
    is_lead_n = s.alive & (s.role == LEADER)          # [N]
    lead_term = jnp.max(jnp.where(is_lead_n, s.term, -1))
    lead_node = jnp.argmax(is_lead_n & (s.term == lead_term)).astype(I32)
    hint_ok = is_lead_n.any() & (s.term == lead_term)  # [N] per contacted node
    ring = (me + 1) % n
    # skip the real leader only when one EXISTS: argmax over all-False is 0,
    # so an unmasked skip would unconditionally dodge node 0 during
    # leaderless windows (ADVICE round-5 finding #5) — the bug-mode ring
    # must stay uniform when there is no leader to hide
    ring = jnp.where(
        is_lead_n.any() & (ring == lead_node), (ring + 1) % n, ring
    )
    hint_n = jnp.where(
        kkn.bug_stale_hint, ring, jnp.where(hint_ok, lead_node, -1)
    )  # [N]
    tgt_oh2 = me[None, :] == target[:, None]           # [nc, n]
    tgt_alive = jnp.any(tgt_oh2 & s.alive[None, :], axis=1)
    tgt_is_lead = jnp.any(tgt_oh2 & is_lead_n[None, :], axis=1)
    tgt_hint = jnp.sum(jnp.where(tgt_oh2, hint_n[None, :], 0), axis=1)
    clerk_leader = jnp.where(
        ~retry, ks.clerk_leader,
        jnp.where(
            tgt_is_lead, target,
            jnp.where(tgt_alive, tgt_hint, -1),
        ),
    )
    # await-reply pacing: a submit that reached an alive leader pauses the
    # clerk for retry_wait ticks (one outstanding RPC, client.rs:56)
    clerk_wait = jnp.where(
        retry & tgt_is_lead, kkn.retry_wait,
        jnp.maximum(ks.clerk_wait - 1, 0),
    )

    raft = s._replace(
        log_term=log_term,
        log_val=log_val,
        log_len=log_len,
        # keep the durability watermark with the log (persist-at-append)
        # so a durability sweep over this layer stays safe
        durable_len=durable_after_append(s, log_len),
        violations=violations,
        first_violation_tick=first_violation_tick,
        # next tick's compaction boundary: never past what we've applied
        compact_floor=applied,
        # the clerk-ack latency folds (service entries carry log_tick 0 —
        # _check_kv_cfg pins p_client_cmd=0, so the raft layer's own
        # commit fold never double-counts a clerk op)
        lat_hist=lat_hist,
        phase_hist=phase_hist,
        phase_ticks=phase_ticks,
        lat_ticks=lat_ticks,
        worst_lat=worst[0],
        worst_phases=worst[1],
        worst_key=worst[2],
        worst_client=worst[3],
        worst_sub=worst[4],
    )
    return KvState(
        raft=raft,
        clerk_seq=clerk_seq,
        clerk_out=clerk_out,
        clerk_key=clerk_key,
        clerk_kind=clerk_kind,
        clerk_acked=clerk_acked,
        clerk_leader=clerk_leader,
        clerk_wait=clerk_wait,
        open_arr=open_arr,
        open_srv=open_srv,
        open_drop=open_drop,
        open_stamp=open_stamp,
        clerk_sub=clerk_sub,
        clerk_app=clerk_app,
        clerk_cmt=clerk_cmt,
        clerk_apl=clerk_apl,
        client_retries=client_retries,
        key_lat_hist=key_lat_hist,
        client_lat_hist=client_lat_hist,
        truth_count=truth_count,
        truth_max_seq=truth_max_seq,
        clerk_get_lo=clerk_get_lo,
        clerk_get_obs=clerk_get_obs,
        clerk_last_obs=clerk_last_obs,
        gets_done=gets_done,
        applied=applied,
        last_seq=last_seq,
        apply_count=apply_count,
        key_hash=key_hash,
        key_count=key_count,
        snap_last_seq=snap_last_seq,
        snap_apply_count=snap_apply_count,
        snap_key_hash=snap_key_hash,
        snap_key_count=snap_key_count,
    )


# ---------------------------------------------------------------------------
# Packed KV carry (ISSUE 11; the raft-layer schema notes live in state.py).
#
# The service fields follow the same EXACT-OR-WIDE rule as the raft layer:
# every width below derives from config.packed_bounds plus the static
# KvConfig, so a value can only exceed its dtype by violating a derived
# bound — and the layout gate (kv_packed_layout_reason) refuses to pack any
# run whose bounds do not hold. The embedded raft group re-derives its
# index/cmd dtypes for the service append rate: a kv tick appends up to
# n_clients client entries plus the leader no-op per node per tick (the
# raft layer's 2-per-tick rule does not hold here), and the log carries
# packed (client, seq, key, kind) ops far above the raft cmd bound.
# ---------------------------------------------------------------------------

# Raft fields the service tick writes (everything else flows through the
# packed raft group untouched on the fused path).
_KV_RAFT_WRITES = (
    "log_term", "log_val", "log_len", "durable_len", "violations",
    "first_violation_tick", "compact_floor", "lat_hist",
    # attribution plane (ISSUE 12): the clerk folds write these raft-level
    # rows too; zero-size with metrics off, so the fused re-pack is free
    "phase_hist", "phase_ticks", "lat_ticks", "worst_lat", "worst_phases",
    "worst_key", "worst_client", "worst_sub",
)


@functools.lru_cache(maxsize=None)
def kv_packed_layout(cfg: SimConfig, kcfg: KvConfig) -> tuple:
    """(raft PackedSpec, service field -> dtype table): the whole width
    derivation for one static (SimConfig, KvConfig) pair — the one place
    the schema, the pack/unpack pair, and the width-pinning tests read.

    Bounds (T = cfg.max_lane_ticks, b = packed_bounds(cfg)):
      seq     <= min(T, _SEQ_LIM - 1)   (a clerk starts at most one op/tick)
      index   <= (n_clients + 1) * T + 1  (submits + leader no-op per node
                                           per tick; applied/apply_count/
                                           key_count are all <= log_len,
                                           which covers bug_skip_dedup's
                                           duplicate applies too)
      cmd     <= _pack(top op)          (the log's value channel carries
                                         packed service ops)
      obs     in {-1} U [0, index]      (Get observations; signed)
    """
    b = packed_bounds(cfg)
    nc, nk = kcfg.n_clients, kcfg.n_keys
    idx_bound = (nc + 1) * b.tick + 1
    cmd_bound = _pack(kcfg, nc - 1, _SEQ_LIM - 1, nk - 1, 3)
    sp = packed_spec_for(cfg, index_bound=idx_bound, cmd_bound=cmd_bound)
    seq = uint_for(min(b.tick, _SEQ_LIM - 1))
    obs = sint_for(idx_bound)
    dts = {
        "clerk_seq": seq,
        "clerk_out": BOOL,
        "clerk_key": uint_for(nk - 1),
        "clerk_kind": U8,
        "clerk_acked": seq,
        "clerk_leader": jnp.int8,      # node id, -1 sentinel (n_nodes <= 16)
        "clerk_wait": sp.tick,         # retry_wait gated <= b.tick
        "open_arr": sp.tick,           # <= 1 arrival per clerk per tick
        "open_srv": sp.tick,           # <= open_arr
        "open_drop": sp.tick,          # <= arrivals
        "open_stamp": sp.tick,         # absolute arrival ticks
        "clerk_sub": sp.tick,
        "clerk_app": sp.tick,          # phase boundary stamps (ISSUE 12)
        "clerk_cmt": sp.tick,
        "clerk_apl": sp.tick,
        "client_retries": sp.tick,     # at most one attempt per tick
        "key_lat_hist": sp.index,      # bucket counts <= acked ops
        "client_lat_hist": sp.index,
        "truth_count": sp.index,
        "truth_max_seq": seq,
        "clerk_get_lo": sp.index,
        "clerk_get_obs": obs,
        "clerk_last_obs": obs,
        "gets_done": sp.tick,          # at most one completion per tick
        "applied": sp.index,
        "last_seq": seq,
        "apply_count": sp.index,
        "key_hash": I32,               # full-width hash by design
        "key_count": sp.index,
        "snap_last_seq": seq,
        "snap_apply_count": sp.index,
        "snap_key_hash": I32,
        "snap_key_count": sp.index,
    }
    return sp, dts


class PackedKvState(NamedTuple):
    """KvState in the packed schema: the raft group as a PackedClusterState
    (service-rate index/cmd dtypes) and every service field narrowed per
    kv_packed_layout. Field names mirror KvState exactly, which is what
    lets pack/unpack and the fused write-back stay table-driven."""

    raft: PackedClusterState
    clerk_seq: jax.Array
    clerk_out: jax.Array
    clerk_key: jax.Array
    clerk_kind: jax.Array
    clerk_acked: jax.Array
    clerk_leader: jax.Array
    clerk_wait: jax.Array
    open_arr: jax.Array
    open_srv: jax.Array
    open_drop: jax.Array
    open_stamp: jax.Array
    clerk_sub: jax.Array
    clerk_app: jax.Array
    clerk_cmt: jax.Array
    clerk_apl: jax.Array
    client_retries: jax.Array
    key_lat_hist: jax.Array
    client_lat_hist: jax.Array
    truth_count: jax.Array
    truth_max_seq: jax.Array
    clerk_get_lo: jax.Array
    clerk_get_obs: jax.Array
    clerk_last_obs: jax.Array
    gets_done: jax.Array
    applied: jax.Array
    last_seq: jax.Array
    apply_count: jax.Array
    key_hash: jax.Array
    key_count: jax.Array
    snap_last_seq: jax.Array
    snap_apply_count: jax.Array
    snap_key_hash: jax.Array
    snap_key_count: jax.Array


def pack_kv_state(cfg: SimConfig, kcfg: KvConfig, ks: KvState) -> PackedKvState:
    sp, dts = kv_packed_layout(cfg, kcfg)
    return PackedKvState(raft=pack_state(cfg, ks.raft, sp),
                         **pack_fields(ks, dts))


def unpack_kv_state(cfg: SimConfig, kcfg: KvConfig,
                    p: PackedKvState) -> KvState:
    sp, dts = kv_packed_layout(cfg, kcfg)
    return KvState(raft=unpack_state(cfg, p.raft, sp),
                   **unpack_fields(p, dts))


def kv_packed_layout_reason(cfg: SimConfig, kcfg: KvConfig, kn, kkn,
                            ticks_needed: int) -> Optional[str]:
    """None when the packed KV schema is exact for this run — else the
    human-readable wide-fallback reason (the state.packed_layout_reason
    contract extended with the kv-layer gates)."""
    r = packed_layout_reason(cfg, kn, ticks_needed)
    if r is not None:
        return r
    k = jax.tree.map(np.asarray, kkn)
    b = packed_bounds(cfg)
    if (k.retry_wait > b.tick).any():
        return (
            f"retry_wait {k.retry_wait} > {b.tick}: the clerk await "
            "countdown packs in the tick dtype"
        )
    return None


def kv_step_packed(
    cfg: SimConfig, kcfg: KvConfig, pks: PackedKvState,
    cluster_key: jax.Array, kn=None, kkn=None,
) -> PackedKvState:
    """One tick over the PACKED KV carry. Default: widen-on-use at the
    whole-state boundary (pack o kv_step o unpack — the ISSUE-9 idiom).
    With cfg.fuse_packed_step the composition is PER FIELD GROUP instead:
    the raft sub-tick consumes and produces the packed raft group, the
    service tick reads a widened VIEW of only the raft fields it touches
    (XLA DCE drops the rest), and only the fields the service WRITES
    (_KV_RAFT_WRITES) are re-packed — the full wide raft pytree never
    materializes between the raft layer and the service apply machines.
    Both paths are bit-identical to the wide step (pack/unpack exactness;
    test-pinned), so the flag is purely a fusion-layout choice."""
    if kn is None:
        _check_kv_cfg(cfg)
        kn = cfg.knobs()
    if kkn is None:
        kkn = kcfg.knobs()
    if not cfg.fuse_packed_step:
        return pack_kv_state(cfg, kcfg, kv_step(
            cfg, kcfg, unpack_kv_state(cfg, kcfg, pks), cluster_key, kn, kkn
        ))
    sp, dts = kv_packed_layout(cfg, kcfg)
    pre = unpack_state(cfg, pks.raft, sp)  # alive/base/shadow_len + the
    #                                        step's own reads survive DCE
    ps = pack_state(cfg, step_cluster(cfg, pre, cluster_key, kn), sp)
    s = unpack_state(cfg, ps, sp)          # the service's widened view
    ks = KvState(raft=s, **unpack_fields(pks, dts))
    nks = _kv_service_tick(cfg, kcfg, ks, pre.alive, pre.base,
                           pre.shadow_len, s, cluster_key, kn, kkn)
    pw = pack_state(cfg, nks.raft, sp)     # only the written fields survive
    raft = ps._replace(**{f: getattr(pw, f) for f in _KV_RAFT_WRITES})
    return PackedKvState(raft=raft, **pack_fields(nks, dts))


# ------------------------------------------------------------------- drivers
class KvFuzzReport(NamedTuple):
    violations: np.ndarray            # i32 bitmask per cluster
    first_violation_tick: np.ndarray  # -1 = none
    acked_ops: np.ndarray             # committed client ops per cluster
    acked_gets: np.ndarray            # completed Gets per cluster
    committed: np.ndarray             # committed log entries per cluster
    msg_count: np.ndarray
    snap_installs: np.ndarray         # install-snapshot deliveries
    # metrics plane (ISSUE 10): clerk submit->ack histograms + liveness
    # counters per cluster; None with cfg.metrics off
    lat_hist: Optional[np.ndarray] = None
    ev_counts: Optional[np.ndarray] = None
    # attribution plane (ISSUE 12): per-phase histograms/tick totals, the
    # per-key/per-client axes, and the per-cluster worst-op registers;
    # None with cfg.metrics off
    phase_hist: Optional[np.ndarray] = None     # [C, n_phases, HB]
    phase_ticks: Optional[np.ndarray] = None    # [C, n_phases]
    lat_ticks: Optional[np.ndarray] = None      # [C, 1]
    key_hist: Optional[np.ndarray] = None       # [C, NK, HB]
    client_hist: Optional[np.ndarray] = None    # [C, NC, HB]
    client_retries: Optional[np.ndarray] = None  # [C, NC]
    worst_lat: Optional[np.ndarray] = None      # [C, 1]
    worst_phases: Optional[np.ndarray] = None   # [C, n_phases]
    worst_key: Optional[np.ndarray] = None      # [C, 1]
    worst_client: Optional[np.ndarray] = None   # [C, 1]
    worst_sub: Optional[np.ndarray] = None      # [C, 1]

    @property
    def n_violating(self) -> int:
        return int((self.violations != 0).sum())

    def violating_clusters(self) -> np.ndarray:
        return np.nonzero(self.violations != 0)[0]


@functools.lru_cache(maxsize=None)
def _kv_program(
    static_cfg: SimConfig, static_kcfg: KvConfig, n_clusters: int,
    mesh: Optional[Mesh], per_cluster_knobs: bool = False,
    packed: bool = False,
):
    """One compiled program per static shape; probabilities, bug modes, and
    the tick count are runtime arguments. Knobs are UNIFORM runtime scalars
    (vmap in_axes=None) — the fast knob layout; per-cluster knob arrays
    measured a 2.4x cliff (see engine._fuzz_program) and are used only by
    ``make_kv_sweep_fn``, which alone pays for its heterogeneity. With
    ``packed`` the fori carry is the PackedKvState (ISSUE 11) — a SEPARATE
    cached program, so the wide HLO is untouched — and the final state is
    widened before returning, so every report/consumer is layout-blind."""
    constraint = None
    if mesh is not None:
        constraint = NamedSharding(mesh, P(mesh.axis_names[0]))
    kn_ax = 0 if per_cluster_knobs else None
    step_fn = kv_step_packed if packed else kv_step

    def run(seed, kn, kkn, n_ticks) -> KvState:
        base = jax.random.PRNGKey(seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(n_clusters)
        )
        states = jax.vmap(
            functools.partial(init_kv_cluster, static_cfg, static_kcfg),
            in_axes=(0, kn_ax),
        )(keys, kn)
        if packed:
            states = jax.vmap(
                functools.partial(pack_kv_state, static_cfg, static_kcfg)
            )(states)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys = jax.lax.with_sharding_constraint(keys, constraint)
            if per_cluster_knobs:
                kn, kkn = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, constraint),
                    (kn, kkn),
                )

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_fn, static_cfg, static_kcfg),
                in_axes=(0, 0, kn_ax, kn_ax),
            )(carry, keys, kn, kkn)

        final = jax.lax.fori_loop(0, n_ticks, body, states)
        if packed:
            final = jax.vmap(
                functools.partial(unpack_kv_state, static_cfg, static_kcfg)
            )(final)
        return final

    return jax.jit(run)


def _kv_layout_telemetry(fn, cfg, kcfg, n_clusters, packed, layout, reason):
    return attach_layout_telemetry(
        fn, n_clusters, packed, layout, reason,
        lambda: pack_kv_state(
            cfg, kcfg, init_kv_cluster(cfg, kcfg, jax.random.PRNGKey(0))
        ),
    )


def make_kv_fuzz_fn(
    cfg: SimConfig,
    kcfg: KvConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """Build fn(seed) -> final batched KvState (see engine.make_fuzz_fn).

    ``pack_states``: None (default) carries the loop state in the packed
    KV schema whenever it is exact for this run (kv_packed_layout_reason);
    True forces it (ValueError when inexact); False forces the wide carry.
    The returned fn carries ``state_layout`` (+ ``state_layout_reason`` on
    a wide fallback) and, when packed, ``state_hbm_bytes``/``bytes_per_lane``
    — surfaced through the CLI fuzz telemetry."""
    _check_kv_cfg(cfg)
    kn = cfg.knobs()    # uniform runtime scalars — the fast knob layout
    kkn = kcfg.knobs()
    reason = kv_packed_layout_reason(cfg, kcfg, kn, kkn, n_ticks)
    packed, layout = choose_layout_from_reason(reason, pack_states)
    prog = _kv_program(cfg.static_key(), kcfg.static_key(), n_clusters, mesh,
                       False, packed)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    # uint32 coercion: keep the (seed, cluster_id) replay contract under x64
    fn = FuzzProgram(
        prog,
        lambda seed: (jnp.asarray(seed, jnp.uint32), kn, kkn, ticks),
    )
    return _kv_layout_telemetry(fn, cfg, kcfg, n_clusters, packed, layout,
                                reason)


def _validate_kv_knobs(kkn) -> None:
    """Eager rejection of service-knob values that would silently misbehave
    inside the compiled program (the engine._validate_knobs analogue)."""
    from madraft_tpu.tpusim.engine import validate_bool_bugs, validate_probs

    k = jax.tree.map(np.asarray, kkn)
    validate_probs(
        k, ("p_op", "p_get", "p_put", "p_retry", "p_follow_hint",
            "open_rate"), "kv"
    )
    if (k.p_get + k.p_put > 1.0).any():
        raise ValueError(
            "p_get + p_put must stay <= 1 per cluster (one uniform draw "
            "splits Get/Put/Append)"
        )
    if ((k.open_queue_cap < 0) | (k.open_queue_cap > OPEN_QUEUE_SLOTS)).any():
        raise ValueError(
            f"open_queue_cap must stay in [0, {OPEN_QUEUE_SLOTS}] (the "
            "arrival-stamp ring size; 0 = closed loop)"
        )
    if (k.zipf_a < 1.0).any():
        raise ValueError(
            "zipf_a must be >= 1.0 (1.0 = the uniform key draw; larger "
            "values skew toward key 0)"
        )
    validate_bool_bugs(
        k, ("bug_skip_dedup", "bug_apply_uncommitted", "bug_stale_read",
            "bug_stale_hint"), "kv"
    )


def make_kv_sweep_fn(
    cfg: SimConfig,
    knobs,   # config.Knobs, uniform or with leading [n_clusters] axes
    kknobs,  # KvKnobs, uniform or with leading [n_clusters] axes
    kcfg: KvConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """Like make_kv_fuzz_fn, but every cluster runs its own raft AND
    service knobs — fault intensity, workload mix, and even the BUG
    injections become per-cluster data, so a whole mutation-testing matrix
    (which clusters run which planted bug) executes in ONE program. The
    layout gate sees the whole knob matrix (every per-cluster value must
    respect the packed bounds, or the sweep falls back to wide)."""
    from madraft_tpu.tpusim.engine import (
        _validate_knobs,
        validate_service_raft_knobs,
    )

    _check_kv_cfg(cfg)
    _validate_knobs(knobs)
    validate_service_raft_knobs(knobs)
    _validate_kv_knobs(kknobs)
    reason = kv_packed_layout_reason(cfg, kcfg, knobs, kknobs, n_ticks)
    packed, layout = choose_layout_from_reason(reason, pack_states)
    prog = _kv_program(cfg.static_key(), kcfg.static_key(), n_clusters, mesh,
                       True, packed)
    kn = knobs.broadcast(n_clusters)
    kkn = kknobs.broadcast(n_clusters)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    fn = FuzzProgram(
        prog,
        lambda seed: (jnp.asarray(seed, jnp.uint32), kn, kkn, ticks),
    )
    return _kv_layout_telemetry(fn, cfg, kcfg, n_clusters, packed, layout,
                                reason)


def kv_report(final: KvState) -> KvFuzzReport:
    has_metrics = final.raft.lat_hist.size > 0

    def m(x):
        return np.asarray(x) if has_metrics else None

    return KvFuzzReport(
        violations=np.asarray(final.raft.violations),
        first_violation_tick=np.asarray(final.raft.first_violation_tick),
        acked_ops=np.asarray(final.clerk_acked.sum(axis=-1)),
        acked_gets=np.asarray(final.gets_done.sum(axis=-1)),
        committed=np.asarray(final.raft.shadow_len),
        msg_count=np.asarray(final.raft.msg_count),
        snap_installs=np.asarray(final.raft.snap_install_count),
        lat_hist=m(final.raft.lat_hist),
        ev_counts=m(final.raft.ev_counts),
        phase_hist=m(final.raft.phase_hist),
        phase_ticks=m(final.raft.phase_ticks),
        lat_ticks=m(final.raft.lat_ticks),
        key_hist=m(final.key_lat_hist),
        client_hist=m(final.client_lat_hist),
        client_retries=m(final.client_retries),
        worst_lat=m(final.raft.worst_lat),
        worst_phases=m(final.raft.worst_phases),
        worst_key=m(final.raft.worst_key),
        worst_client=m(final.raft.worst_client),
        worst_sub=m(final.raft.worst_sub),
    )


def kv_fuzz(
    cfg: SimConfig,
    kcfg: KvConfig,
    seed: int,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
) -> KvFuzzReport:
    """Fuzz the KV service over n_clusters independent simulated clusters."""
    fn = make_kv_fuzz_fn(cfg, kcfg, n_clusters, n_ticks, mesh=mesh)
    final = jax.block_until_ready(fn(jnp.asarray(seed, jnp.uint32)))
    return kv_report(final)


@functools.lru_cache(maxsize=None)
def _kv_replay_program(static_cfg: SimConfig, static_kcfg: KvConfig,
                       packed: bool = False):
    step_fn = kv_step_packed if packed else kv_step

    def run(cluster_id, kn, kkn, n_ticks, seed):
        ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
        state = init_kv_cluster(static_cfg, static_kcfg, ckey, kn)
        if packed:
            state = pack_kv_state(static_cfg, static_kcfg, state)

        def body(_, carry):
            return step_fn(static_cfg, static_kcfg, carry, ckey, kn, kkn)

        final = jax.lax.fori_loop(0, n_ticks, body, state)
        if packed:
            final = unpack_kv_state(static_cfg, static_kcfg, final)
        return final

    return jax.jit(run)


def kv_replay_cluster(
    cfg: SimConfig, kcfg: KvConfig, seed: int, cluster_id: int, n_ticks: int,
    pack_states: Optional[bool] = None,
) -> KvState:
    """Re-run one cluster for inspection (the (seed, cluster_id) replay
    contract). Layout-blind: the packed carry replays bit-identically to
    the wide one (test-pinned), and the returned state is always wide."""
    _check_kv_cfg(cfg)
    kn, kkn = cfg.knobs(), kcfg.knobs()
    packed, _ = choose_layout_from_reason(
        kv_packed_layout_reason(cfg, kcfg, kn, kkn, n_ticks), pack_states
    )
    prog = _kv_replay_program(cfg.static_key(), kcfg.static_key(), packed)
    return jax.block_until_ready(
        prog(jnp.asarray(cluster_id, jnp.int32), kn, kkn,
             jnp.asarray(n_ticks, jnp.int32), jnp.asarray(seed, jnp.uint32))
    )
