"""On-device metrics plane helpers (ISSUE 10; ROADMAP item 4).

The instrumentation itself lives where the state lives — step.py folds
commit latencies and counts liveness events, kv.py/shardkv.py fold clerk
submit->ack latencies — and this module is the ONE copy of everything
around it: the log-spaced bucket layout, the device-side fold, the
host-side quantile decode, and the merge/render utilities the reports,
bench gate, and the `stats` CLI verb share.

Bucket convention (config.HIST_BUCKETS fixed log-spaced buckets):
  bucket 0        latency in [0, 1] ticks
  bucket k >= 1   latency in [2^k, 2^(k+1) - 1]
  last bucket     open-ended: [2^(HB-1), inf)
Quantile decode (``quantile_from_hist``) reports the UPPER edge of the
bucket holding the quantile — a conservative estimate whose error is
bounded by the bucket width — except the open-ended last bucket, which
reports its lower edge (the best defensible number it has). Fixed edges
mean histograms merge by plain addition: per-lane rows sum into a pool
summary, shard rows sum at harvest, and report files sum in `stats`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import (
    HIST_BUCKETS,
    METRIC_EVENTS,
    phase_names,
)

I32 = jnp.int32

# Lower edges of buckets 1..HB-1 (bucket 0's lower edge is 0). Shared by
# the device fold and the host decode so the two cannot disagree about the
# layout; the cross-check test recomputes bucket indices via a DIFFERENT
# host implementation (np.searchsorted) on raw stamps.
BUCKET_EDGES = tuple(1 << k for k in range(1, HIST_BUCKETS))


def fold_latencies(hist: jnp.ndarray, lat: jnp.ndarray,
                   mask: jnp.ndarray) -> jnp.ndarray:
    """Device-side fold: add each masked latency's bucket to ``hist``
    ([HIST_BUCKETS] i32). ``lat``/``mask`` are any matching shape; the
    fold is a one-hot sum (no scatters — the TPU idiom everywhere else in
    the step). Draw-free by construction, and statically so: the lint
    draw-parity groups (tpusim/lint.py) pin metrics-on programs to the
    same random_bits site count as metrics-off."""
    edges = jnp.asarray(BUCKET_EDGES, I32)
    flat_lat = lat.reshape(-1)
    flat_mask = mask.reshape(-1)
    bucket = jnp.sum(
        (flat_lat[:, None] >= edges[None, :]).astype(I32), axis=1
    )  # [m] in [0, HB-1]
    oh = (
        jnp.arange(HIST_BUCKETS, dtype=I32)[None, :] == bucket[:, None]
    ) & flat_mask[:, None]
    return hist + jnp.sum(oh, axis=0, dtype=I32)


def fold_latencies_by(hist2d: jnp.ndarray, lat: jnp.ndarray,
                      mask: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Row-attributed fold (ISSUE 12): add each masked latency's bucket to
    ROW ``idx[i]`` of ``hist2d`` ([rows, HIST_BUCKETS]) — the per-key /
    per-client attribution axes. Same one-hot-sum idiom as fold_latencies
    (no scatters), with a second one-hot over the row axis."""
    rows = hist2d.shape[0]
    edges = jnp.asarray(BUCKET_EDGES, I32)
    bucket = jnp.sum((lat[:, None] >= edges[None, :]).astype(I32), axis=1)
    b_oh = jnp.arange(HIST_BUCKETS, dtype=I32)[None, :] == bucket[:, None]
    r_oh = jnp.arange(rows, dtype=I32)[None, :] == idx[:, None]  # [m, rows]
    hit = (r_oh[:, :, None] & b_oh[:, None, :]) & mask[:, None, None]
    return hist2d + jnp.sum(hit, axis=0, dtype=I32)


def fold_phases(phase_hist: jnp.ndarray, phase_ticks: jnp.ndarray,
                lat_ticks: jnp.ndarray, phases: jnp.ndarray,
                lat: jnp.ndarray, mask: jnp.ndarray) -> tuple:
    """The phase-decomposition fold (ISSUE 12): for every masked acked op,
    fold EACH phase duration into that phase's histogram row (zeros land in
    bucket 0, so every phase row's mass equals the acked-op count — the
    same hist-sum==acked invariant shape as the e2e histogram) and
    accumulate the exact tick totals. ``phases`` is [n_phases, m]; the
    per-op invariant sum(phases[:, i]) == lat[i] is the caller's contract
    (each call site derives phases as consecutive stamp differences, so it
    holds by construction) and makes sum(phase_ticks) == lat_ticks exact —
    test-pinned end to end."""
    new_hist = jax.vmap(lambda h, p: fold_latencies(h, p, mask))(
        phase_hist, phases
    )
    new_ticks = phase_ticks + jnp.sum(
        jnp.where(mask[None, :], phases, 0), axis=1, dtype=I32
    )
    new_lat = lat_ticks + jnp.sum(jnp.where(mask, lat, 0), dtype=I32)
    return new_hist, new_ticks, new_lat


def clerk_phase_matrix(t, sub, app, cmt, apl, is_get):
    """Exact 4-phase decomposition [n_phases, NC] of the e2e latency
    ``t - sub`` from the clerk boundary stamps (config.LATENCY_PHASES
    order). The boundaries are clamped monotone (sub <= app <= cmt <= b3
    <= t), so the rows always telescope to exactly t - sub — the pinned
    phase-sum invariant holds per op by construction, not by bookkeeping.
    Shared by the kv and ctrler clerks; shardkv extends it with the
    migration row."""
    app_e = jnp.maximum(app, sub)
    cmt_e = jnp.maximum(cmt, app_e)
    b3 = jnp.where(is_get, jnp.maximum(apl, cmt_e), cmt_e)
    return jnp.stack([app_e - sub, cmt_e - app_e, b3 - cmt_e, t - b3])


def update_worst(reg: tuple, lat: jnp.ndarray, mask: jnp.ndarray,
                 phases: jnp.ndarray, keys: jnp.ndarray,
                 clients: jnp.ndarray, subs: jnp.ndarray) -> tuple:
    """Per-lane worst-op register update (ISSUE 12): among this tick's
    masked acks, the argmax-latency op replaces the register when it beats
    the held worst (or the register is empty — worst_sub 0 means no op
    captured yet; real submit stamps are >= 1). ``reg`` is the 5-tuple
    (worst_lat [1], worst_phases [n_phases], worst_key [1],
    worst_client [1], worst_sub [1]); deterministic tie-breaking: ties
    keep the held op (strict >), and within a tick argmax picks the
    lowest index."""
    worst_lat, worst_phases, worst_key, worst_client, worst_sub = reg
    i = jnp.argmax(jnp.where(mask, lat, -1))
    oh = jnp.arange(lat.shape[0], dtype=I32) == i

    def sel(x):
        return jnp.sum(jnp.where(oh, x, 0), axis=-1, dtype=I32)

    cand = sel(lat)
    better = jnp.any(mask) & ((cand > worst_lat[0]) | (worst_sub[0] == 0))
    return (
        jnp.where(better, cand, worst_lat[0])[None],
        jnp.where(better, sel(phases), worst_phases),
        jnp.where(better, sel(keys), worst_key[0])[None],
        jnp.where(better, sel(clients), worst_client[0])[None],
        jnp.where(better, sel(subs), worst_sub[0])[None],
    )


def phases_summary(phase_hist, phase_ticks,
                   ms_per_tick: Optional[int] = None) -> dict:
    """The ``latency.phases`` dict every report surface carries: one
    latency_summary per phase row, keyed BY NAME (layers with different
    phase sets merge by name downstream), plus the exact tick total — the
    attribution readout (which phase the tail lives in)."""
    names = phase_names(len(phase_hist))
    pt = np.asarray(phase_ticks, np.int64)
    out = {}
    for p, name in enumerate(names):
        d = latency_summary(phase_hist[p], ms_per_tick)
        d["ticks_total"] = int(pt[p])
        out[name] = d
    return out


def worst_op_dict(worst_lat, worst_phases, worst_key, worst_client,
                  worst_sub) -> Optional[dict]:
    """Decode one worst-op register into the report dict (None when the
    register is empty — no op ever acked on this lane)."""
    if int(np.asarray(worst_sub).reshape(-1)[0]) == 0:
        return None
    names = phase_names(np.asarray(worst_phases).reshape(-1).shape[0])
    ph = np.asarray(worst_phases, np.int64).reshape(-1)
    return {
        "latency_ticks": int(np.asarray(worst_lat).reshape(-1)[0]),
        "submit_tick": int(np.asarray(worst_sub).reshape(-1)[0]),
        "key": int(np.asarray(worst_key).reshape(-1)[0]),
        "client": int(np.asarray(worst_client).reshape(-1)[0]),
        "phases": {name: int(ph[p]) for p, name in enumerate(names)},
    }


def merge_worst(a: Optional[dict], b: Optional[dict],
                a_id=None, b_id=None) -> Optional[dict]:
    """Deterministic merge of two worst-op dicts (each may carry a
    ``cluster_id``): higher latency wins; ties break toward the smaller
    cluster id, so the pool-summary worst op is device-count invariant
    (the retired-row multiset is)."""
    if a is not None and a_id is not None and "cluster_id" not in a:
        a = {**a, "cluster_id": int(a_id)}
    if b is not None and b_id is not None and "cluster_id" not in b:
        b = {**b, "cluster_id": int(b_id)}
    if a is None:
        return b
    if b is None:
        return a
    ka = (a["latency_ticks"], -a.get("cluster_id", 0))
    kb = (b["latency_ticks"], -b.get("cluster_id", 0))
    return a if ka >= kb else b


def merge_worst_registers(worst_lat, worst_phases, worst_key,
                          worst_client, worst_sub, ids=None,
                          into: Optional[dict] = None) -> Optional[dict]:
    """Merge a batch of per-lane worst-op registers (leading axis = lanes)
    into one dict under the merge_worst rule — THE one copy of the
    register-decode loop shared by the report JSON, the pool accounting,
    and bench's tail_attrib row. ``ids`` labels each lane's cluster id
    (defaults to the lane index); ``into`` seeds the merge."""
    worst = into
    for c in range(np.asarray(worst_lat).shape[0]):
        worst = merge_worst(
            worst,
            worst_op_dict(worst_lat[c], worst_phases[c], worst_key[c],
                          worst_client[c], worst_sub[c]),
            b_id=int(ids[c]) if ids is not None else c,
        )
    return worst


def host_bucket(lat: np.ndarray) -> np.ndarray:
    """Host-side bucket index per latency — deliberately a DIFFERENT
    implementation (searchsorted over the edges) than the device fold, so
    the traced-replay cross-check exercises the layout, not one shared
    function."""
    return np.searchsorted(np.asarray(BUCKET_EDGES), np.asarray(lat),
                           side="right")


def bucket_bounds(k: int) -> tuple:
    """(lower, upper) latency bounds of bucket k; upper is None for the
    open-ended last bucket."""
    lo = 0 if k == 0 else (1 << k)
    hi = None if k == HIST_BUCKETS - 1 else (1 << (k + 1)) - 1
    return lo, hi


def quantile_from_hist(hist, q: float) -> Optional[int]:
    """The q-quantile latency estimate (ticks) from a merged histogram:
    the upper edge of the bucket where the cumulative count first reaches
    q * total (lower edge for the open-ended last bucket). None when the
    histogram is empty."""
    h = np.asarray(hist, dtype=np.int64)
    total = int(h.sum())
    if total == 0:
        return None
    k = int(np.searchsorted(np.cumsum(h), q * total, side="left"))
    k = min(k, HIST_BUCKETS - 1)
    lo, hi = bucket_bounds(k)
    return lo if hi is None else hi


def latency_summary(hist, ms_per_tick: Optional[int] = None) -> dict:
    """The latency dict every report surface carries: observed-op count,
    p50/p99 decoded from the buckets, and the raw histogram row (so the
    dict itself stays mergeable downstream — `stats` re-sums these)."""
    h = np.asarray(hist, dtype=np.int64)
    out = {
        "ops": int(h.sum()),
        "p50_ticks": quantile_from_hist(h, 0.50),
        "p99_ticks": quantile_from_hist(h, 0.99),
        "hist": [int(x) for x in h],
    }
    if ms_per_tick and out["p99_ticks"] is not None:
        out["p50_ms"] = out["p50_ticks"] * ms_per_tick
        out["p99_ms"] = out["p99_ticks"] * ms_per_tick
    return out


def hist_window(now, prev) -> np.ndarray:
    """Window delta of two cumulative histogram snapshots. Fixed edges
    make this exact: cumulative histograms only ever grow by addition, so
    the delta IS the histogram of the window's samples, and summing every
    window row of a heartbeat stream reproduces the cumulative histogram
    bit-for-bit (the ``stats`` merge relies on this)."""
    h = np.asarray(now, dtype=np.int64)
    if prev is None:
        return h.copy()
    return h - np.asarray(prev, dtype=np.int64)


def window_latency(now, prev) -> dict:
    """The heartbeat row's windowed latency digest: op count and p50/p99
    decoded from the WINDOW histogram (``*_w`` column convention), plus the
    raw window row so downstream merges stay additive."""
    h = hist_window(now, prev)
    return {
        "ops_w": int(h.sum()),
        "p50_w": quantile_from_hist(h, 0.50),
        "p99_w": quantile_from_hist(h, 0.99),
        "hist_w": [int(x) for x in h],
    }


def window_phase_ticks(now, prev) -> dict:
    """Per-phase exact tick totals for one window, keyed by name (the same
    by-name convention as phases_summary, so heartbeat phase columns merge
    with report phases downstream)."""
    d = hist_window(now, prev)
    return {name: int(d[p]) for p, name in enumerate(phase_names(len(d)))}


def event_summary(ev) -> dict:
    """METRIC_EVENTS-keyed counter dict from one merged ev_counts row."""
    ev = np.asarray(ev, dtype=np.int64)
    return {name: int(ev[i]) for i, name in enumerate(METRIC_EVENTS)}


def render_histogram(hist, width: int = 40) -> list:
    """ASCII rendering of one merged histogram (the `stats` verb body):
    one line per non-empty bucket range, bar scaled to the largest."""
    h = np.asarray(hist, dtype=np.int64)
    total = int(h.sum())
    if total == 0:
        return ["(no latency samples)"]
    top = int(h.max())
    lines = []
    cum = 0
    for k in range(HIST_BUCKETS):
        if h[k] == 0:
            continue
        cum += int(h[k])
        lo, hi = bucket_bounds(k)
        rng = f"[{lo}, {hi}]" if hi is not None else f"[{lo}, inf)"
        bar = "#" * max(1, round(width * int(h[k]) / top))
        lines.append(
            f"{rng:>16} ticks  {int(h[k]):>10}  {100.0 * cum / total:5.1f}%  "
            f"{bar}"
        )
    return lines
