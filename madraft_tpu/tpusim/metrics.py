"""On-device metrics plane helpers (ISSUE 10; ROADMAP item 4).

The instrumentation itself lives where the state lives — step.py folds
commit latencies and counts liveness events, kv.py/shardkv.py fold clerk
submit->ack latencies — and this module is the ONE copy of everything
around it: the log-spaced bucket layout, the device-side fold, the
host-side quantile decode, and the merge/render utilities the reports,
bench gate, and the `stats` CLI verb share.

Bucket convention (config.HIST_BUCKETS fixed log-spaced buckets):
  bucket 0        latency in [0, 1] ticks
  bucket k >= 1   latency in [2^k, 2^(k+1) - 1]
  last bucket     open-ended: [2^(HB-1), inf)
Quantile decode (``quantile_from_hist``) reports the UPPER edge of the
bucket holding the quantile — a conservative estimate whose error is
bounded by the bucket width — except the open-ended last bucket, which
reports its lower edge (the best defensible number it has). Fixed edges
mean histograms merge by plain addition: per-lane rows sum into a pool
summary, shard rows sum at harvest, and report files sum in `stats`.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import HIST_BUCKETS, METRIC_EVENTS

I32 = jnp.int32

# Lower edges of buckets 1..HB-1 (bucket 0's lower edge is 0). Shared by
# the device fold and the host decode so the two cannot disagree about the
# layout; the cross-check test recomputes bucket indices via a DIFFERENT
# host implementation (np.searchsorted) on raw stamps.
BUCKET_EDGES = tuple(1 << k for k in range(1, HIST_BUCKETS))


def fold_latencies(hist: jnp.ndarray, lat: jnp.ndarray,
                   mask: jnp.ndarray) -> jnp.ndarray:
    """Device-side fold: add each masked latency's bucket to ``hist``
    ([HIST_BUCKETS] i32). ``lat``/``mask`` are any matching shape; the
    fold is a one-hot sum (no scatters — the TPU idiom everywhere else in
    the step)."""
    edges = jnp.asarray(BUCKET_EDGES, I32)
    flat_lat = lat.reshape(-1)
    flat_mask = mask.reshape(-1)
    bucket = jnp.sum(
        (flat_lat[:, None] >= edges[None, :]).astype(I32), axis=1
    )  # [m] in [0, HB-1]
    oh = (
        jnp.arange(HIST_BUCKETS, dtype=I32)[None, :] == bucket[:, None]
    ) & flat_mask[:, None]
    return hist + jnp.sum(oh, axis=0, dtype=I32)


def host_bucket(lat: np.ndarray) -> np.ndarray:
    """Host-side bucket index per latency — deliberately a DIFFERENT
    implementation (searchsorted over the edges) than the device fold, so
    the traced-replay cross-check exercises the layout, not one shared
    function."""
    return np.searchsorted(np.asarray(BUCKET_EDGES), np.asarray(lat),
                           side="right")


def bucket_bounds(k: int) -> tuple:
    """(lower, upper) latency bounds of bucket k; upper is None for the
    open-ended last bucket."""
    lo = 0 if k == 0 else (1 << k)
    hi = None if k == HIST_BUCKETS - 1 else (1 << (k + 1)) - 1
    return lo, hi


def quantile_from_hist(hist, q: float) -> Optional[int]:
    """The q-quantile latency estimate (ticks) from a merged histogram:
    the upper edge of the bucket where the cumulative count first reaches
    q * total (lower edge for the open-ended last bucket). None when the
    histogram is empty."""
    h = np.asarray(hist, dtype=np.int64)
    total = int(h.sum())
    if total == 0:
        return None
    k = int(np.searchsorted(np.cumsum(h), q * total, side="left"))
    k = min(k, HIST_BUCKETS - 1)
    lo, hi = bucket_bounds(k)
    return lo if hi is None else hi


def latency_summary(hist, ms_per_tick: Optional[int] = None) -> dict:
    """The latency dict every report surface carries: observed-op count,
    p50/p99 decoded from the buckets, and the raw histogram row (so the
    dict itself stays mergeable downstream — `stats` re-sums these)."""
    h = np.asarray(hist, dtype=np.int64)
    out = {
        "ops": int(h.sum()),
        "p50_ticks": quantile_from_hist(h, 0.50),
        "p99_ticks": quantile_from_hist(h, 0.99),
        "hist": [int(x) for x in h],
    }
    if ms_per_tick and out["p99_ticks"] is not None:
        out["p50_ms"] = out["p50_ticks"] * ms_per_tick
        out["p99_ms"] = out["p99_ticks"] * ms_per_tick
    return out


def event_summary(ev) -> dict:
    """METRIC_EVENTS-keyed counter dict from one merged ev_counts row."""
    ev = np.asarray(ev, dtype=np.int64)
    return {name: int(ev[i]) for i, name in enumerate(METRIC_EVENTS)}


def render_histogram(hist, width: int = 40) -> list:
    """ASCII rendering of one merged histogram (the `stats` verb body):
    one line per non-empty bucket range, bar scaled to the largest."""
    h = np.asarray(hist, dtype=np.int64)
    total = int(h.sum())
    if total == 0:
        return ["(no latency samples)"]
    top = int(h.max())
    lines = []
    cum = 0
    for k in range(HIST_BUCKETS):
        if h[k] == 0:
            continue
        cum += int(h[k])
        lo, hi = bucket_bounds(k)
        rng = f"[{lo}, {hi}]" if hi is not None else f"[{lo}, inf)"
        bar = "#" * max(1, round(width * int(h[k]) / top))
        lines.append(
            f"{rng:>16} ticks  {int(h[k]):>10}  {100.0 * cum / total:5.1f}%  "
            f"{bar}"
        )
    return lines
