"""Host-side live-telemetry plane (ISSUE 17; ROADMAP item 4).

A long-running pool or soak is opaque between launch and final summary:
the pipeline timers, the latency plane, and the coverage curve are only
SUMMED into the end-of-run summary. This module is the one copy of the
plane that fixes that — a heartbeat stream (one JSONL row per harvest
generation) plus an atomically-replaced run manifest an external watcher
can use to discover a live run and distinguish crashed from running from
done. Everything here runs on the host, off the hot path: the engine
calls it only from the PR-7 harvest-consumer thread, on already-fetched
numpy arrays, so the compiled-program set is untouched (the lint registry
pin and the golden fuzz/pool guards say so statically).

Heartbeat row schema (v1) — two clearly-separated column groups:

  {"hb": 1, "gen": G, "lane_ticks": T, ["final": true,]
   "det": { ... },       # DETERMINISTIC: pure functions of
                         # (seed, config, chunk cadence, budget_ticks) —
                         # device-count invariant (1-vs-2, lane scheme)
                         # and state-layout blind, test-pinned
   "t":   { ... }}       # TIMING: wall clock, rates, per-generation
                         # pipeline deltas, ETA — explicitly NOT
                         # deterministic, never compared across runs

``det`` carries: retired / violating cumulative + ``*_w`` window counts,
``effective_steps``, the coverage discovery counters when the run is a
coverage pool (``new_fps``/``new_fps_w``/``refills_*`` — deterministic per
FIXED device count only: per-shard novelty is topology-dependent), and a
``latency`` sub-dict (window ops/p50/p99 + the raw window histogram and
per-phase tick totals, merged via metrics.py's fixed-bucket fold) when the
metrics plane is on. ``t`` carries wall_s, window violations/s and fp/s,
the per-generation dispatch_gap_s / device_wait_s / host_overlap_s deltas
from the pipeline, and budget_frac / eta_s against the run budget.

The manifest ``<heartbeat>.manifest.json`` is REPLACED atomically
(tmp + os.replace) on every generation, so it is always valid JSON:

  {"schema": 1, "status": "running" | "done" | "failed", "pid": ...,
   "heartbeat": <basename>, "context": {config echo, static_key, seed,
   lanes, horizon, chunk_ticks, devices, budget}, "last_gen": G,
   "lane_ticks": T, "retired": R, "violating": V, "updated_unix": ...}

``manifest_status`` folds in pid liveness: a manifest stuck at "running"
whose pid is gone reads as "crashed" — the watcher-side tri-state. (Pid
liveness is same-host only; a watcher on another machine sees "running"
until the writer's terminal update.)

This module imports nothing heavier than numpy at module scope so the
C++-side soak (_cpp_soak.py) and the `stats` verb (which skips backend
init entirely) can use it without touching JAX.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

HEARTBEAT_SCHEMA = 1


# ------------------------------------------------------------- manifest
def manifest_path(heartbeat_path) -> str:
    """The manifest's one naming rule: ``<heartbeat>.manifest.json``."""
    return str(heartbeat_path) + ".manifest.json"


def write_json_atomic(path: str, doc: dict) -> None:
    """tmp + os.replace: a reader (or an abrupt kill) can never observe a
    half-written file — the _soak checkpoint convention, promoted here."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def read_manifest(path: str) -> Optional[dict]:
    """Load a manifest (tolerates a heartbeat path — resolves the naming
    rule). None when absent or unparsable mid-replace is impossible by
    construction, so unparsable means 'not a manifest'."""
    if not path.endswith(".manifest.json"):
        path = manifest_path(path)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError):
        return False
    return True


def manifest_status(doc: Optional[dict]) -> str:
    """The watcher tri-state: 'done' / 'failed' are terminal as written;
    'running' with a dead pid decays to 'crashed' (the writer never got to
    its terminal update); anything unreadable is 'unknown'."""
    if not isinstance(doc, dict) or "status" not in doc:
        return "unknown"
    status = doc["status"]
    if status != "running":
        return status
    return "running" if pid_alive(doc.get("pid", -1)) else "crashed"


def is_terminal(status: str) -> bool:
    return status in ("done", "failed", "crashed")


# ------------------------------------------------------- heartbeat reader
def read_heartbeat(lines) -> list:
    """Parse heartbeat rows out of a line iterable (skips anything that
    isn't a v-known hb row — pool JSONL reports interleave freely)."""
    rows = []
    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            doc = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and doc.get("hb") == HEARTBEAT_SCHEMA:
            rows.append(doc)
    return rows


def digest_line(row: dict) -> str:
    """The one-line human digest of a heartbeat row (`pool`'s stderr
    --digest-every cadence and the soaks share this spelling):
    ``gen 12 · 38% of budget · viol/s 0.41 · p99 127``."""
    det = row.get("det", {})
    t = row.get("t", {})
    parts = [f"gen {row.get('gen', '?')}"]
    frac = t.get("budget_frac")
    if frac is not None:
        parts.append(f"{100.0 * frac:.0f}% of budget")
    vps = t.get("viol_per_s")
    if vps is not None:
        parts.append(f"viol/s {vps}")
    lat = det.get("latency")
    if isinstance(lat, dict) and lat.get("p99_w") is not None:
        parts.append(f"p99 {lat['p99_w']}")
    fps = t.get("fp_per_s_w")
    if fps is not None:
        parts.append(f"fp/s {fps}")
    return " · ".join(parts)


# -------------------------------------------------------------- the writer
class HeartbeatWriter:
    """One heartbeat stream + manifest for one run.

    Construction is cheap and JAX-free; ``open(context)`` binds the run
    (the engine calls it before its warm-up so the manifest exists the
    moment the run is discoverable). ``path=None`` keeps the row pipeline
    (generation counting, ``on_row`` digests) without any file output —
    what `pool --digest-every` without --heartbeat uses.

    Thread contract: after ``open``, every method runs on ONE thread (the
    pool's harvest consumer; the soaks' main thread) — same no-locking
    rule as _PoolAccount.
    """

    def __init__(self, path=None, *,
                 on_row: Optional[Callable[[dict], None]] = None):
        self.path = str(path) if path else None
        self.on_row = on_row
        self.context: dict = {}
        self.gen = 0
        self._f = None
        self._snap: Optional[dict] = None  # previous cumulative snapshot

    # ------------------------------------------------------------ plumbing
    def open(self, context: dict) -> None:
        self.context = dict(context)
        if self.path:
            self._f = open(self.path, "w")
            self._manifest("running")

    def _manifest(self, status: str, **extra) -> None:
        if not self.path:
            return
        doc = {
            "schema": HEARTBEAT_SCHEMA,
            "status": status,
            "pid": os.getpid(),
            "heartbeat": os.path.basename(self.path),
            "context": self.context,
            "last_gen": self.gen - 1 if self.gen else None,
            "updated_unix": round(time.time(), 3),
            **extra,
        }
        write_json_atomic(manifest_path(self.path), doc)

    def row(self, det: dict, t: dict, lane_ticks=None,
            final: bool = False) -> dict:
        """Emit one raw row (the soaks' direct entry; ``generation`` and
        ``final_row`` build the pool rows on top of this)."""
        doc = {"hb": HEARTBEAT_SCHEMA, "gen": self.gen}
        if lane_ticks is not None:
            doc["lane_ticks"] = int(lane_ticks)
        if final:
            doc["final"] = True
        doc["det"] = det
        doc["t"] = t
        if self._f is not None:
            self._f.write(json.dumps(doc) + "\n")
            self._f.flush()
        self.gen += 1
        self._manifest("running", lane_ticks=doc.get("lane_ticks"),
                       retired=det.get("retired"),
                       violating=det.get("violating"))
        if self.on_row is not None:
            self.on_row(doc)
        return doc

    def close(self, status: str = "done") -> None:
        """Terminal manifest update + stream close. Idempotent, and safe
        to call with no prior open (a run that died before warming)."""
        if self._f is not None:
            self._f.close()
            self._f = None
            self._manifest(status)

    # ------------------------------------------------- pool-account bridge
    def _cumulative(self, acct) -> dict:
        """Snapshot the account's cumulative counters (copies the mutable
        arrays so window deltas are against a frozen point)."""
        import numpy as np

        snap = {
            "retired": acct.retired_total,
            "violating": acct.viol_total,
            "effective": int(acct.effective),
            "seen_fps": acct.seen_prev,
            "refills_mutated": acct.refills_mutated,
            "refills_fresh": acct.refills_fresh,
            "hist": None,
            "phase_ticks": None,
        }
        if acct.hist_total is not None:
            snap["hist"] = np.array(acct.hist_total, np.int64)
            snap["phase_ticks"] = np.array(acct.phase_ticks_total, np.int64)
        return snap

    def _det(self, acct, cov: bool, now: dict, prev: Optional[dict]) -> dict:
        from madraft_tpu.tpusim import metrics as _metrics

        p = prev or {}
        det = {
            "retired": now["retired"],
            "retired_w": now["retired"] - p.get("retired", 0),
            "violating": now["violating"],
            "violating_w": now["violating"] - p.get("violating", 0),
            "effective_steps": now["effective"],
        }
        if cov:
            det["new_fps"] = now["seen_fps"]
            det["new_fps_w"] = now["seen_fps"] - p.get("seen_fps", 0)
            det["refills_mutated"] = now["refills_mutated"]
            det["refills_fresh"] = now["refills_fresh"]
        if now["hist"] is not None:
            det["latency"] = _metrics.window_latency(
                now["hist"], p.get("hist"))
            det["latency"]["phase_ticks_w"] = _metrics.window_phase_ticks(
                now["phase_ticks"], p.get("phase_ticks"))
        return det

    def _timing(self, det: dict, wall: float, timing: Optional[dict],
                prev_wall: float) -> dict:
        t = {"wall_s": round(wall, 4)}
        if wall > 0:
            t["viol_per_s"] = round(det["violating"] / wall, 3)
        dw = wall - prev_wall
        if dw > 0:
            t["viol_per_s_w"] = round(det["violating_w"] / dw, 3)
            if "new_fps_w" in det:
                t["fp_per_s_w"] = round(det["new_fps_w"] / dw, 2)
        for k in ("dispatch_gap_s", "device_wait_s", "host_overlap_s"):
            if timing and k in timing:
                t[k] = round(timing[k], 5)
        frac = None
        bt = self.context.get("budget_ticks")
        bs = self.context.get("budget_seconds")
        if bt and timing and timing.get("lane_ticks"):
            frac = min(1.0, timing["lane_ticks"] / bt)
        elif bs:
            frac = min(1.0, wall / bs)
        if frac is not None:
            t["budget_frac"] = round(frac, 4)
            if 0 < frac < 1:
                t["eta_s"] = round(wall * (1.0 - frac) / frac, 2)
        return t

    def generation(self, acct, wall: float,
                   timing: Optional[dict]) -> None:
        """One per-harvest-generation row, called from _PoolAccount.consume
        on the consumer thread (numpy only — never into JAX)."""
        now = self._cumulative(acct)
        cov = bool(acct.new_fp_per_gen)
        det = self._det(acct, cov, now, self._snap)
        prev_wall = (self._snap or {}).get("wall", 0.0)
        t = self._timing(det, wall, timing, prev_wall)
        now["wall"] = wall
        lane_ticks = timing.get("lane_ticks") if timing else None
        self._snap = now
        self.row(det, t, lane_ticks=lane_ticks)

    def final_row(self, acct, lane_ticks: int, wall: float,
                  tele: dict) -> None:
        """The reconciliation row after acct.finish(): cumulative columns
        equal to the pool summary EXACTLY (test-pinned), with the finish
        window (in-flight lanes) as this row's ``*_w`` deltas so a stats
        merge over the whole stream sums to the run total."""
        from madraft_tpu.tpusim import metrics as _metrics

        now = self._cumulative(acct)
        cov = bool(acct.new_fp_per_gen)
        det = self._det(acct, cov, now, self._snap)
        if now["hist"] is not None:
            # the summary-facing cumulative latency digest, next to the
            # finish-window fields _det computed
            cum = _metrics.latency_summary(now["hist"])
            det["latency"].update({
                "ops": cum["ops"],
                "p50_ticks": cum["p50_ticks"],
                "p99_ticks": cum["p99_ticks"],
                "ticks_total": acct.lat_ticks_total,
            })
        prev_wall = (self._snap or {}).get("wall", 0.0)
        t = self._timing(det, wall, None, prev_wall)
        for k in ("dispatch_gap_s", "device_wait_s", "host_overlap_s"):
            if k in tele:
                t[k] = tele[k]
        self.row(det, t, lane_ticks=lane_ticks, final=True)


def as_writer(heartbeat) -> Optional[HeartbeatWriter]:
    """The engine's coercion rule: None passes through, a path becomes a
    writer, a writer is used as-is (what `pool --digest-every` hands in)."""
    if heartbeat is None or isinstance(heartbeat, HeartbeatWriter):
        return heartbeat
    return HeartbeatWriter(heartbeat)
