"""ctypes bindings to the C++ deterministic-simulation runtime (libmadtpu).

The in-process end of the TPU<->C++ differential bridge (SURVEY.md §7
architecture item 4 calls for Python<->C++ bindings; pybind11 is not in the
build image, so the C ABI of ``cpp/tools/capi.cpp`` is bound with ctypes).
Each call runs a full simcore simulation to completion on the calling
thread — no subprocess fork/exec per replay, which matters when a fuzzing
loop cross-checks many violating clusters. ``madraft_tpu.bridge`` routes
through these bindings when the shared library is loadable and falls back
to the CLI binaries otherwise.

Thread-safety: the C API serializes every call behind one mutex (the replay
knobs ride in process-global env vars, and concurrent setenv/getenv is
undefined behavior) — concurrent Python threads are safe but get no
parallelism; use multiple processes for parallel replays.
"""

from __future__ import annotations

import ctypes
import fcntl
import json
import pathlib
import shutil
import subprocess
from typing import Optional

_REPO = pathlib.Path(__file__).resolve().parent.parent
_LIB_PATH = _REPO / "build" / "libmadtpu.so"
_OUT_CAP = 4096

_lib: Optional[ctypes.CDLL] = None


def _build_lib() -> None:
    build = _REPO / "build"
    build.mkdir(exist_ok=True)
    # serialize concurrent builders (pytest workers, parallel bridge runs):
    # two cmake/ninja invocations in one build dir corrupt each other
    with open(build / ".madtpu_build.lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        # no cmake OR no ninja on this machine: the shared library is ONE
        # translation-unit set — build it directly with the system compiler
        # (gcc 10 needs the explicit -fcoroutines). Checked up front so a
        # REAL cmake-path build failure (both tools present, sources broken)
        # still surfaces cmake's own diagnostics.
        if shutil.which("cmake") is None or shutil.which("ninja") is None:
            _build_lib_gxx(build)
            return
        for cmd in (
            ["cmake", "-S", str(_REPO / "cpp"), "-B", str(build), "-G",
             "Ninja"],
            ["ninja", "-C", str(build), "madtpu"],
        ):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"{' '.join(cmd)} failed:\n{proc.stdout[-1000:]}\n"
                    f"{proc.stderr[-3000:]}"
                )


def _build_lib_gxx(build: pathlib.Path) -> None:
    cpp = _REPO / "cpp"
    cmd = [
        "g++", "-std=c++20", "-fcoroutines", "-O2", "-g", "-fPIC", "-shared",
        "-Wall", "-Wextra", "-Wno-unused-parameter",
        "-I", str(cpp / "simcore"), "-I", str(cpp / "raftcore"),
        str(cpp / "simcore" / "simcore.cpp"),
        str(cpp / "raftcore" / "raft.cpp"),
        str(cpp / "tools" / "capi.cpp"),
        "-o", str(_LIB_PATH),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"g++ fallback build failed:\n{proc.stderr[-3000:]}"
        )


def load(build_if_missing: bool = True) -> ctypes.CDLL:
    """Load (building on demand) and memoize the shared library."""
    global _lib
    if _lib is not None:
        return _lib
    srcs = list((_REPO / "cpp").rglob("*.cpp")) + list((_REPO / "cpp").rglob("*.h"))
    # no cpp tree (e.g. a deployed wheel): use whatever library exists
    newest = max((p.stat().st_mtime for p in srcs), default=0.0)
    if build_if_missing and srcs and (
        not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < newest
    ):
        _build_lib()
    lib = ctypes.CDLL(str(_LIB_PATH))
    for name in ("madtpu_replay_run", "madtpu_shardkv_replay_run",
                 "madtpu_ctrler_replay_run"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        fn.restype = ctypes.c_int
    lib.madtpu_lincheck_run.argtypes = [ctypes.c_char_p]
    lib.madtpu_lincheck_run.restype = ctypes.c_int
    _lib = lib
    return lib


def available() -> bool:
    """True if the bindings can be used (library present or buildable)."""
    try:
        load()
        return True
    except (RuntimeError, OSError, ValueError):
        return False


def _run(fn_name: str, schedule_text: str) -> dict:
    lib = load()
    cap = _OUT_CAP
    if "trace 1" in schedule_text:
        # a traced replay exports per-tick state (~100 bytes/tick): size the
        # buffer up front so the grow-and-retry loop below (which re-runs
        # the whole deterministic sim per attempt) stays a backstop, not
        # the common path
        for line in schedule_text.splitlines():
            if line.startswith("ticks "):
                cap = max(cap, 4096 + 256 * int(line.split()[1]))
                break
    while True:
        out = ctypes.create_string_buffer(cap)
        rc = getattr(lib, fn_name)(schedule_text.encode(), out, cap)
        if rc == -1:
            raise ValueError(f"{fn_name}: bad schedule")
        if rc == -2:
            raise RuntimeError(f"{fn_name}: sim deadlocked")
        if rc == -3 and cap < (1 << 26):
            # report outgrew the buffer (traced replays export per-tick
            # state, ~100 bytes/tick): re-run with a bigger one. The replay
            # is deterministic, so the re-run returns the identical report.
            cap *= 4
            continue
        if rc < 0:
            raise RuntimeError(f"{fn_name}: rc={rc}")
        return json.loads(out.value.decode())


def replay_schedule(schedule_text: str) -> dict:
    """Replay a raw-raft fault schedule in process -> the JSON report dict
    (same schema as the madtpu_replay CLI)."""
    return _run("madtpu_replay_run", schedule_text)


def replay_shardkv_schedule(schedule_text: str) -> dict:
    """Replay a shardkv config+fault schedule in process -> JSON report
    (same schema as the madtpu_shardkv_replay CLI). The bug mode rides in
    the schedule text and is restored after the run."""
    return _run("madtpu_shardkv_replay_run", schedule_text)


def replay_ctrler_schedule(schedule_text: str) -> dict:
    """Apply a 4A committed-op schedule to the real ShardInfo state machine
    in process -> JSON report (same schema as the madtpu_ctrler_replay CLI).
    The planted-bug name rides in the schedule text and is restored after."""
    return _run("madtpu_ctrler_replay_run", schedule_text)


def check_linearizable(history_text: str) -> bool:
    """Run the Wing-Gong checker on a history (lincheck format) in process."""
    rc = load().madtpu_lincheck_run(history_text.encode())
    if rc < 0:
        raise ValueError("bad history text")
    return rc == 1
