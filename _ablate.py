"""Phase-cost attribution for step_cluster by early-return surgery.

Methodology (hardened in round 3 — see PERF.md "Round-3 measurement
caveat" and the verify skill's tunnel notes): the tunnel's ~63 ms
per-call latency and ~±8% run-to-run spread make single-shot timings
meaningless, so every variant is compiled up front and the timed runs are
INTERLEAVED (round-robin across variants, direction alternating), with
best-of reported. Deltas under ~10% are still noise — XLA dead-code-
eliminates differently per truncated variant, so treat the output as a
RANKING of phase cost, not an exact budget, and confirm any conclusion
with a cut-one A/B of the specific phase (the /tmp harness pattern in
PERF.md's kv/shardkv sections).

Usage: python _ablate.py [n_clusters] [scan_len] [reps]
"""
import functools
import json
import pathlib
import sys
import time
import types

import jax
import jax.numpy as jnp
import numpy as np

SRC = pathlib.Path(__file__).with_name("madraft_tpu").joinpath(
    "tpusim", "step.py"
).read_text()

# Anchor = line that starts a section; we insert an early return just before it.
RETURN = (
    "    return s._replace(tick=t, term=term, voted_for=voted_for, role=role,\n"
    "        timer=timer, hb=hb, alive=alive, adj=adj, log_term=log_term,\n"
    "        log_val=log_val, log_len=log_len, base=base, snap_term=snap_term,\n"
    "        commit=commit, votes=votes, next_idx=next_idx, match_idx=match_idx)\n"
)
# Round-3 phase order: responses deliver BEFORE requests (see step.py).
ANCHORS = [
    ("faults-only", "    # ---------------------------------------------------- deliver: RV responses"),
    ("+responses", "    # ------------------------------------------- deliver: install-snapshot"),
    ("+sn-deliver", "    # ----------------------------------------------------- deliver: RV requests"),
    ("+rv-deliver", "    # ----------------------------------------------------- deliver: AE requests"),
    ("+ae-deliver", "    # Candidate -> leader on majority"),
    ("+win", "    # ------------------------------------------------- timers: election timeout"),
    ("+timers", "    # --------------------------------------- client command injection at leaders"),
    ("+inject", "    # -------------------------------------------- leader heartbeat / replication"),
    ("+heartbeat", "    # ------------------------------------------------------------ commit advance"),
    ("+commit", "    # ------------------------------------------------------------------- oracle"),
    ("+oracle", "    # -------------------------------------------------------------- compaction"),
]


def make_step(cut_anchor):
    src = SRC
    if cut_anchor is not None:
        i = src.index(cut_anchor)
        src = src[:i] + RETURN
    mod = types.ModuleType("step_var")
    sys.modules["step_var"] = mod
    exec(compile(src, "step_var.py", "exec"), mod.__dict__)
    return mod.step_cluster


def main():
    from madraft_tpu.tpusim import SimConfig
    from madraft_tpu.tpusim.state import init_cluster

    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01,
                    p_restart=0.2, max_dead=2, p_repartition=0.02, p_heal=0.05)
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    scan_len = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 6
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(n))
    state0 = jax.block_until_ready(
        jax.vmap(functools.partial(init_cluster, cfg))(keys)
    )

    names = [nm for nm, _ in ANCHORS] + ["full"]
    cuts = [a for _, a in ANCHORS] + [None]
    runs = {}
    for name, cut in zip(names, cuts):
        step = make_step(cut)

        @jax.jit
        def run(states, keys, step=step):
            def body(c, _):
                return jax.vmap(functools.partial(step, cfg))(c, keys), None
            return jax.lax.scan(body, states, None, length=scan_len)[0]

        _ = np.asarray(run(state0, keys).tick)  # compile + warm
        runs[name] = run

    times = {name: [] for name in names}
    for r in range(reps):
        order = names if r % 2 == 0 else names[::-1]
        for name in order:
            t0 = time.perf_counter()
            _ = np.asarray(runs[name](state0, keys).tick)
            times[name].append(time.perf_counter() - t0)

    prev = 0.0
    for name in names:
        best = min(times[name]) / scan_len * 1e3
        print(json.dumps({
            "variant": name,
            "ms_per_tick": round(best, 3),
            "delta_ms": round(best - prev, 3),
            "runs": reps,
        }), flush=True)
        prev = best


if __name__ == "__main__":
    main()
