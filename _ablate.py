"""Locate the hot phase of step_cluster by early-return surgery on its source."""
import functools, time, sys, types, pathlib
import jax, jax.numpy as jnp, numpy as np

SRC = pathlib.Path("/root/repo/madraft_tpu/tpusim/step.py").read_text()

# Anchor = line that starts a section; we insert an early return just before it.
RETURN = (
    "    return s._replace(tick=t, term=term, voted_for=voted_for, role=role,\n"
    "        timer=timer, hb=hb, alive=alive, adj=adj, log_term=log_term,\n"
    "        log_val=log_val, log_len=log_len, base=base, snap_term=snap_term,\n"
    "        commit=commit, votes=votes, next_idx=next_idx, match_idx=match_idx)\n"
)
# Round-3 phase order: responses deliver BEFORE requests (see step.py).
ANCHORS = [
    ("faults-only", "    # ---------------------------------------------------- deliver: RV responses"),
    ("+responses", "    # ------------------------------------------- deliver: install-snapshot"),
    ("+sn-deliver", "    # ----------------------------------------------------- deliver: RV requests"),
    ("+rv-deliver", "    # ----------------------------------------------------- deliver: AE requests"),
    ("+ae-deliver", "    # Candidate -> leader on majority"),
    ("+win", "    # ------------------------------------------------- timers: election timeout"),
    ("+timers", "    # --------------------------------------- client command injection at leaders"),
    ("+inject", "    # -------------------------------------------- leader heartbeat / replication"),
    ("+heartbeat", "    # ------------------------------------------------------------ commit advance"),
    ("+commit", "    # ------------------------------------------------------------------- oracle"),
    ("+oracle", "    # -------------------------------------------------------------- compaction"),
]

def make_step(cut_anchor):
    src = SRC
    if cut_anchor is not None:
        i = src.index(cut_anchor)
        src = src[:i] + RETURN
    mod = types.ModuleType("step_var")
    mod.__dict__["__name__"] = "step_var"
    exec(compile(src, "step_var.py", "exec"), mod.__dict__)
    return mod.step_cluster

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.state import init_cluster

cfg = SimConfig(n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01,
                p_restart=0.2, max_dead=2, p_repartition=0.02, p_heal=0.05)
N = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
L = 16
base = jax.random.PRNGKey(0)
keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(N))
states = jax.block_until_ready(jax.vmap(functools.partial(init_cluster, cfg))(keys))

names = [n for n, _ in ANCHORS] + ["full"]
cuts = [a for _, a in ANCHORS] + [None]
prev = 0.0
for name, cut in zip(names, cuts):
    step = make_step(cut)
    @jax.jit
    def run(states, keys, step=step):
        def body(c, _):
            return jax.vmap(functools.partial(step, cfg))(c, keys), None
        final, _ = jax.lax.scan(body, states, None, length=L)
        return final
    out = run(states, keys); _ = np.asarray(out.tick)  # compile+run+fetch
    t0 = time.time(); out = run(states, keys); _ = np.asarray(out.tick)
    dt = (time.time() - t0) / L * 1e3
    print(f"{name:12s} {dt:8.2f} ms/tick  (delta {dt-prev:+8.2f})", flush=True)
    prev = dt
