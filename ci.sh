#!/usr/bin/env bash
# One-command CI: reproduces the full green state locally.
# Mirrors the reference's CI split (/root/reference/.github/workflows/ci.yml:11-43
# build+lint job, test.yml:20-26 test job) for this framework's two backends:
#
#   1. C++ build (Release) + full 70-test suite on 2 seeds
#   2. C++ determinism double-run (trace-hash compare; the madsim
#      MADSIM_TEST_CHECK_DETERMINISTIC analogue, reference README.md:42-87)
#   3. C++ ASan build + suite (memory safety for the coroutine runtime)
#   4. Python/TPU-sim suite on the virtual CPU device mesh (conftest.py)
#   5. Static lint gate (ISSUE 15): jaxpr passes over every registered
#      program — clean registry exits 0, planted-defect selftest exits 1
#   6. Bench smoke (small cluster batch; CPU unless a TPU is attached)
#
# Usage: ./ci.sh [--fast]        (--fast skips ASan and the second seed)
#        ./ci.sh --soak [N]      (nightly: N-seed C++ suite soak via
#                                 _cpp_soak.py, default 500, then exit)
set -euo pipefail
cd "$(dirname "$0")"
FAST=${1:-}

if [ "$FAST" = "--soak" ]; then
  N=${2:-500}
  cmake -S cpp -B build -G Ninja -DCMAKE_BUILD_TYPE=Release >/dev/null
  ninja -C build >/dev/null
  SOAK_OUT=${SOAK_OUT:-SOAK_cpp_nightly.json} python _cpp_soak.py "$N"
  exit $?
fi

echo "== [1/6] C++ Release build + tests (seed 12345, 2 seeds + regression seed 7036)"
cmake -S cpp -B build -G Ninja -DCMAKE_BUILD_TYPE=Release >/dev/null
ninja -C build >/dev/null
MADTPU_TEST_SEED=12345 MADTPU_TEST_NUM=$([ "$FAST" = "--fast" ] && echo 1 || echo 2) \
  ./build/madtpu_tests | tail -1
# seed 7036: the round-4 soak's deterministic shardkv liveness hang (PERF.md
# round 5 — config starvation via the linearizable clerk path); keep it green
MADTPU_TEST_SEED=7036 ./build/madtpu_tests shardkv_challenge2_unaffected_4b | tail -1

echo "== [2/6] C++ determinism double-run"
MADTPU_TEST_SEED=424242 MADTPU_TEST_CHECK_DETERMINISTIC=1 \
  ./build/madtpu_tests | tail -1

if [ "$FAST" != --fast ]; then
  echo "== [3/6] C++ ASan build + tests"
  cmake -S cpp -B build-asan -G Ninja -DCMAKE_BUILD_TYPE=Debug \
    -DCMAKE_CXX_FLAGS="-fsanitize=address,undefined -fno-omit-frame-pointer" \
    -DCMAKE_EXE_LINKER_FLAGS="-fsanitize=address,undefined" >/dev/null
  ninja -C build-asan >/dev/null
  MADTPU_TEST_SEED=12345 ./build-asan/madtpu_tests | tail -1
else
  echo "== [3/6] skipped (--fast)"
fi

echo "== [4/6] Python/TPU-sim suite (virtual CPU device mesh)"
# MADTPU_SHARDKV_CACHE_WRITE=1: conftest reorders shardkv FIRST in full-suite
# runs (young process, outside the round-5 serialize-crash zone), so its
# multi-minute compiles may safely land in .jax_cache and deserialize on
# every later run — mirrors the tpusim job in .github/workflows/ci.yml
MADTPU_SHARDKV_CACHE_WRITE=1 \
  python -m pytest tests/ --ignore tests/test_cpp_suite.py -q
# durability smoke + flight-recorder smoke + hot-path guard (ISSUE 2). The
# golden "clean" leg IS the durability-storm smoke (same argv: the correct
# algorithm under total un-fsynced suffix loss must report zero violations
# and exit 0); the "bug" leg must exit 1; both fixed-seed fuzz REPORTs must
# match the pre-PR golden bit-identically (tracing/telemetry add zero
# hot-path cost); and the planted-bug cluster must decode to a non-empty
# explain timeline (explain is a debugging tool — exit 0).
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json, pathlib
from madraft_tpu.__main__ import main

golden = json.loads(pathlib.Path("tests/golden_fuzz.json").read_text())
for leg, want_rc in (("clean", 0), ("bug", 1)):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(golden[leg]["argv"])
    assert rc == want_rc, f"[{leg}] fuzz exit {rc} != {want_rc}"
    live = json.loads(buf.getvalue().strip().splitlines()[-1])
    for k, want in golden[leg]["report"].items():
        assert live[k] == want, f"hot-path drift [{leg}] {k}: {live[k]} != {want}"
# explain the golden bug leg's first violating cluster — coordinates come
# from the golden file so a deliberate regeneration cannot strand them here
bad = golden["bug"]["report"]["violating_clusters"][0]
opts = dict(zip(golden["bug"]["argv"][1::2], golden["bug"]["argv"][2::2]))
buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["explain", "--profile", opts["--profile"],
               "--bug", opts["--bug"], "--seed", opts["--seed"],
               "--ticks", opts["--ticks"], "--cluster", str(bad),
               "--window", "25"])
lines = buf.getvalue().strip().splitlines()
header = json.loads(lines[0])
assert rc == 0 and len(lines) > 1, "explain must exit 0 with a timeline"
assert header["violation_names"], header
print(f"explain smoke: {len(lines) - 1} events, "
      f"names={header['violation_names']}, "
      f"first_violation_tick={header['first_violation_tick']}; "
      "fixed-seed fuzz golden OK")
PY

# pool smoke (ISSUE 5 + ISSUE 9 packed path): the continuous
# retire-and-refill pool on the durability profile, which now carries the
# PACKED state layout (the golden file above already pins that the packed
# carry retires bit-identical clusters). The planted-bug leg must retire
# >= 1 violating cluster within its budget and exit 1 (violations are
# findings, like fuzz); the clean leg must retire everything at the
# horizon and exit 0. Both legs must report state_layout "packed"; the
# re-widening regression the old bytes_per_lane <= 2800 bench ceiling
# caught after the fact is now pinned STATICALLY — per-field dtype pins in
# tests/test_width_pin.py plus the lint packed_width pass (step 5) — so
# this smoke only checks the layout choice, not the byte total.
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json
from madraft_tpu.__main__ import main

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--bug", "ack_before_fsync",
               "--clusters", "64", "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "600", "--seed", "1"])
lines = [json.loads(x) for x in buf.getvalue().strip().splitlines()]
summary = lines[-1]
assert rc == 1, f"pool bug leg exit {rc} != 1"
assert summary["retired_violating"] >= 1, summary
assert summary["state_layout"] == "packed", summary
rows = [r for r in lines[:-1] if r.get("violations")]
assert rows and rows[0]["cluster_id"] in summary["violating_clusters"], rows

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--clusters", "64",
               "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "300", "--seed", "12345"])
summary = json.loads(buf.getvalue().strip().splitlines()[-1])
assert rc == 0, f"pool clean leg exit {rc} != 0"
assert summary["retired_violating"] == 0 and summary["retired"] == 64, summary
assert summary["state_layout"] == "packed", summary
print(f"pool smoke: bug leg retired {len(rows)} violating "
      f"(first={rows[0]['cluster_id']}), clean leg 64/64 at horizon, "
      f"packed layout at {summary['bytes_per_lane']} B/lane")
PY

# coverage smoke (ISSUE 6): the coverage-GUIDED pool on the planted-bug
# profile must still retire >= 1 violating cluster (generation 1 is
# bit-identical to the plain pool; only refill policy differs after), must
# report a nonzero new-fingerprint count, and its JSONL rows must carry the
# coverage columns (new_fingerprints / refill / knobs) that make mutated
# lanes replayable. Coverage programs are separate cached programs, so this
# leg's compiles never touch the plain pool's warm cache entries.
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json
from madraft_tpu.__main__ import main

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--bug", "ack_before_fsync",
               "--clusters", "64", "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "600", "--seed", "1", "--coverage"])
lines = [json.loads(x) for x in buf.getvalue().strip().splitlines()]
summary, rows = lines[-1], lines[:-1]
assert rc == 1, f"coverage bug leg exit {rc} != 1"
assert summary["retired_violating"] >= 1, summary
cov = summary["coverage"]
assert cov["guided"] and cov["seen_fingerprints"] > 0, cov
assert any(r["new_fingerprints"] > 0 for r in rows), "no lane discovered"
assert all("refill" in r and "knobs" in r for r in rows)
print(f"coverage smoke: {summary['retired_violating']} violating, "
      f"{cov['seen_fingerprints']} fingerprints over "
      f"{cov['generations']} generations "
      f"(mutated {cov['refills_mutated']}, fresh {cov['refills_fresh']})")
PY

# metrics smoke (ISSUE 10 + 12): the on-device metrics plane through the
# pool. The planted-bug leg must report nonzero histogram mass (summary
# latency dict + per-row latency_hist/events columns), the attribution
# plane (latency.phases keyed by phase name, per-row latency_phases, a
# worst_op register) must ride along with the phase-sum invariant intact,
# the packed layout must hold the METRICS-ON bytes bound (3585 B/lane
# measured at this shape in round 12 vs 3417 pre-attribution; the 3600
# ceiling catches attribution-axis growth the way the metrics-off 2800
# gate catches re-widening), and the `stats` verb must render the captured
# stream; the clean leg is the latency-tail REGRESSION GATE — the
# durability profile's clean p99 must stay under the pinned bound
# (bench.py's storm tail_gate analogue; 255 ticks measured at this shape
# in round 10, 511 = one log-spaced bucket of headroom, so only a real
# distribution shift trips it). Metrics are a static program flag
# (SimConfig.metrics joins static_key), so these legs select their own
# cached programs and the metrics-off pool smoke above stays bit-identical.
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json, tempfile
from madraft_tpu.__main__ import main

DURABILITY_P99_BOUND = 511  # ticks; clean-leg p99 measured 255 (round 10)
# metrics-on byte pin (was <= 3600 here): static in tests/test_width_pin.py

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--bug", "ack_before_fsync",
               "--clusters", "64", "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "600", "--seed", "1", "--metrics"])
stream = buf.getvalue()
lines = [json.loads(x) for x in stream.strip().splitlines()]
summary, rows = lines[-1], lines[:-1]
assert rc == 1, f"metrics bug leg exit {rc} != 1"
assert summary["retired_violating"] >= 1, summary
lat = summary["latency"]
assert lat["ops"] > 0, lat
assert summary["events"]["commit_advances"] > 0, summary["events"]
assert all("latency_hist" in r and "events" in r for r in rows), \
    "JSONL rows missing the metrics columns"
# attribution plane (ISSUE 12): phase rows + worst op, summary and rows
assert summary["state_layout"] == "packed", summary
phases = lat["phases"]
assert set(phases) == {"leader_wait", "replicate", "apply", "ack"}, phases
assert all(sum(d["hist"]) == lat["ops"] for d in phases.values()), \
    "each phase row must fold one sample per acked op"
assert sum(d["ticks_total"] for d in phases.values()) == lat["ticks_total"], \
    "phase tick totals must sum to the e2e latency total exactly"
w = summary["worst_op"]
assert w and sum(w["phases"].values()) == w["latency_ticks"], w
assert all("latency_phases" in r and "worst_op" in r for r in rows), \
    "JSONL rows missing the attribution columns"
# cross-surface mass accounting: the summary merges the retired rows PLUS
# the final harvest's in-flight lanes, so the independent per-row columns
# must carry nonzero mass and never exceed the merged total
row_mass = sum(sum(r["latency_hist"]) for r in rows)
assert 0 < row_mass <= lat["ops"], (row_mass, lat["ops"])
with tempfile.NamedTemporaryFile("w", suffix=".jsonl") as f:
    f.write(stream); f.flush()
    sbuf = io.StringIO()
    with contextlib.redirect_stdout(sbuf):
        src = main(["stats", f.name])
    assert src == 0 and f"ops={lat['ops']}" in sbuf.getvalue(), \
        "stats verb failed to render the pool stream"

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--clusters", "64",
               "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "300", "--seed", "12345", "--metrics"])
clean = json.loads(buf.getvalue().strip().splitlines()[-1])
assert rc == 0, f"metrics clean leg exit {rc} != 0"
clat = clean["latency"]
assert clat["ops"] > 0, clat
assert clat["p99_ticks"] <= DURABILITY_P99_BOUND, (
    f"latency TAIL GATE failed: clean durability p99 {clat['p99_ticks']} > "
    f"{DURABILITY_P99_BOUND} ticks"
)
print(f"metrics smoke: bug leg {lat['ops']} ops "
      f"(p50={lat['p50_ticks']} p99={lat['p99_ticks']}), stats verb OK, "
      f"clean-leg tail gate p99 {clat['p99_ticks']} <= "
      f"{DURABILITY_P99_BOUND}")
PY

# heartbeat smoke (ISSUE 17): the live-telemetry plane through the pool
# CLI. The planted-bug leg streams one JSONL row per harvest generation to
# --heartbeat; the final row's deterministic columns must reconcile EXACTLY
# with the pool summary (same retire accounting, observed not recomputed),
# the sibling manifest must land terminal status "done", and `stats` must
# render the live stream. The clean leg pins that the plane never perturbs
# the exit-code convention (0 = no violation). Telemetry is host-side only
# — the lint registry's cached-program pin (tests/test_lint.py, exactly 31)
# is the static proof the hot path gained zero new compiled programs.
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json, os, tempfile
from madraft_tpu.__main__ import main
from madraft_tpu.tpusim.telemetry import manifest_path, manifest_status

d = tempfile.mkdtemp()
hb = os.path.join(d, "ci_hb.jsonl")
buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--bug", "ack_before_fsync",
               "--clusters", "64", "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "600", "--seed", "1", "--heartbeat", hb])
summary = json.loads(buf.getvalue().strip().splitlines()[-1])
assert rc == 1, f"heartbeat bug leg exit {rc} != 1"
with open(hb) as f:
    rows = [json.loads(x) for x in f if x.strip()]
assert rows and rows[-1].get("final"), rows[-1:]
fin = rows[-1]["det"]
assert fin["retired"] == summary["retired"], (fin, summary["retired"])
assert fin["violating"] == summary["retired_violating"]
assert fin["effective_steps"] == summary["effective_cluster_steps"]
assert rows[-1]["lane_ticks"] == summary["lane_ticks"]
man = json.load(open(manifest_path(hb)))
assert manifest_status(man) == "done" and man["last_gen"] == rows[-1]["gen"]
sbuf = io.StringIO()
with contextlib.redirect_stdout(sbuf):
    src = main(["stats", hb])
assert src == 0 and "final" in sbuf.getvalue(), \
    "stats verb failed to render the heartbeat stream"

hb2 = os.path.join(d, "ci_hb_clean.jsonl")
buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--clusters", "64",
               "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "300", "--seed", "12345",
               "--heartbeat", hb2])
assert rc == 0, f"heartbeat clean leg exit {rc} != 0"
assert manifest_status(json.load(open(manifest_path(hb2)))) == "done"
print(f"heartbeat smoke: {len(rows)} rows, final gen {rows[-1]['gen']} "
      f"reconciles with summary (retired {fin['retired']}, "
      f"{fin['violating']} violating), manifest done")
PY

# service packed-state smoke (ISSUE 11): the kv/ctrler/shardkv fuzz verbs
# carry their loop state in the packed SERVICE schemas at the default
# shapes — each leg must report state_layout "packed" in its telemetry,
# and the shardkv deployment widths (formerly bytes <= 14000 here) are
# pinned field-by-field in tests/test_width_pin.py — static, no run
# needed. The kv/ctrler runs are clean (exit 0); packed-vs-wide report
# bit-identity itself is pinned by tests/test_service_layout.py.
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json
from madraft_tpu.__main__ import main

def run(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, json.loads(buf.getvalue().strip().splitlines()[-1])

rc, d = run(["kv-fuzz", "--clusters", "32", "--ticks", "128", "--seed", "3"])
assert rc == 0, f"kv-fuzz exit {rc}"
kv_tele = d["telemetry"]
assert kv_tele["state_layout"] == "packed", kv_tele

rc, d = run(["ctrler-fuzz", "--clusters", "32", "--ticks", "128",
             "--seed", "3"])
assert rc == 0, f"ctrler-fuzz exit {rc}"
assert d["telemetry"]["state_layout"] == "packed", d["telemetry"]

rc, d = run(["shardkv-fuzz", "--nodes", "3", "--clusters", "8",
             "--ticks", "160", "--seed", "3"])
assert rc == 0, f"shardkv-fuzz exit {rc}"
tele = d["telemetry"]
assert tele["state_layout"] == "packed", tele
print(f"service packed smoke: kv {kv_tele['bytes_per_lane']} B/lane, "
      f"shardkv {tele['bytes_per_lane']} B/deployment, all legs packed")
PY

# gray-failure game-day smoke (ISSUE 19): the gray profiles through the
# pool CLI. Clean legs on `limp` (limping senders) and `fsync_stall` (the
# widest ack_before_fsync window any profile offers) must stay violation-
# free AND live — the per-profile liveness floor and p99 ceiling come from
# config.profile_gates(), the same source bench's gate table enforces —
# and the heartbeat manifest must echo the active profile name (the
# ISSUE 19 additive field; MIGRATION.md). The planted-bug leg re-arms
# ack_before_fsync under the stall profile: the durability oracles must
# fire (exit 1) — the stall axis exists to widen exactly that window.
MADTPU_PLATFORM=cpu python - <<'PY'
import contextlib, io, json, os, tempfile
from madraft_tpu.__main__ import main
from madraft_tpu.tpusim.config import profile_gates
from madraft_tpu.tpusim.telemetry import manifest_path

def run(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, [json.loads(x) for x in buf.getvalue().strip().splitlines()]

gates = profile_gates()
d = tempfile.mkdtemp()
floors = {}
for prof in ("limp", "fsync_stall"):
    hb = os.path.join(d, f"ci_gray_{prof}.jsonl")
    rc, lines = run(["pool", "--profile", prof, "--clusters", "64",
                     "--ticks", "300", "--chunk-ticks", "100",
                     "--budget-ticks", "300", "--seed", "12345",
                     "--metrics", "--heartbeat", hb])
    s = lines[-1]
    assert rc == 0, f"gray clean leg [{prof}] exit {rc} != 0"
    assert s["retired_violating"] == 0 and s["retired"] == 64, s
    assert s["state_layout"] == "packed", s
    g = gates[prof]
    lat = s["latency"]
    ops_per_lane = lat["ops"] / 64
    assert ops_per_lane >= g["liveness_floor"], (
        f"[{prof}] liveness floor breach: {ops_per_lane:.2f} ops/lane < "
        f"{g['liveness_floor']} — the gray axis starved the cluster"
    )
    assert lat["p99_ticks"] <= g["p99_ceiling"], (
        f"[{prof}] p99 ceiling breach: {lat['p99_ticks']} > "
        f"{g['p99_ceiling']} ticks"
    )
    ctx = json.load(open(manifest_path(hb)))["context"]
    assert ctx["profile"] == prof, ctx.get("profile")
    floors[prof] = (round(ops_per_lane, 2), lat["p99_ticks"])

rc, lines = run(["pool", "--profile", "fsync_stall", "--bug",
                 "ack_before_fsync", "--clusters", "64", "--ticks", "300",
                 "--chunk-ticks", "100", "--budget-ticks", "600",
                 "--seed", "1"])
s = lines[-1]
assert rc == 1, f"gray bug leg exit {rc} != 1"
assert s["retired_violating"] >= 1, (
    "fsync_stall failed to surface ack_before_fsync — the stall axis no "
    "longer widens the volatile window"
)
print("gray smoke: clean legs " + ", ".join(
    f"{p} {o} ops/lane p99={q}" for p, (o, q) in floors.items())
    + f" within gates; stall bug leg retired {s['retired_violating']} "
    "violating, manifest echoes profile")
PY

# sharded-pool smoke (ISSUE 7): the pod-scale lane-partitioned pool on the
# 2-virtual-device CI config. The planted-bug leg must retire >= 1 violating
# cluster and exit 1; the clean leg must retire everything at the horizon
# and exit 0; the coverage leg proves the coverage+mesh gate is lifted
# (per-shard seen-set, union-counted fingerprints). Reports at any device
# count are the same multiset (tests/test_pool.py pins 1-vs-2 equality).
MADTPU_PLATFORM=cpu JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=2" python - <<'PY'
import contextlib, io, json
from madraft_tpu.__main__ import main

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--bug", "ack_before_fsync",
               "--clusters", "64", "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "600", "--seed", "1", "--devices", "2"])
lines = [json.loads(x) for x in buf.getvalue().strip().splitlines()]
summary = lines[-1]
assert rc == 1, f"sharded pool bug leg exit {rc} != 1"
assert summary["retired_violating"] >= 1, summary
assert summary["devices"] == 2 and summary["id_scheme"] == "lane", summary
rows = [r for r in lines[:-1] if r.get("violations")]
assert rows and rows[0]["cluster_id"] in summary["violating_clusters"], rows

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--clusters", "64",
               "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "300", "--seed", "12345", "--devices", "2"])
clean = json.loads(buf.getvalue().strip().splitlines()[-1])
assert rc == 0, f"sharded pool clean leg exit {rc} != 0"
assert clean["retired_violating"] == 0 and clean["retired"] == 64, clean

buf = io.StringIO()
with contextlib.redirect_stdout(buf):
    rc = main(["pool", "--profile", "durability", "--bug", "ack_before_fsync",
               "--clusters", "64", "--ticks", "300", "--chunk-ticks", "100",
               "--budget-ticks", "600", "--seed", "1", "--coverage",
               "--devices", "2"])
lines = [json.loads(x) for x in buf.getvalue().strip().splitlines()]
cov = lines[-1]["coverage"]
assert rc == 1 and lines[-1]["retired_violating"] >= 1, lines[-1]
assert cov["shards"] == 2 and cov["seen_fingerprints"] > 0, cov
assert all("refill" in r and "knobs" in r for r in lines[:-1])
print(f"sharded pool smoke: bug leg retired "
      f"{summary['retired_violating']} violating on 2 shards, clean leg "
      f"64/64 at horizon, coverage leg {cov['seen_fingerprints']} union "
      f"fingerprints (gap {summary['dispatch_gap_s']}s, overlap "
      f"{summary['host_overlap_s']}s)")
PY

echo "== [5/6] static lint gate (jaxpr passes over every cached program)"
# ISSUE 15: trace-only — every registry program lints green (exit 0) and
# the JSON report lands as a CI artifact; then the planted-defect selftest
# must exit 1, proving the analyzer still catches each defect class (a
# lint that silently stopped finding anything would otherwise look green).
# The 2-virtual-device CPU mesh matches conftest.py so the sharded entries
# trace instead of skipping.
LINT_ENV="MADTPU_PLATFORM=cpu JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2"
env $LINT_ENV python -m madraft_tpu lint --json lint_report.json
if env $LINT_ENV python -m madraft_tpu lint --selftest >/dev/null; then
  echo "lint --selftest exited 0: planted defects were NOT caught" >&2
  exit 1
fi
echo "lint selftest: planted defects caught (exit 1 as expected)"

echo "== [6/6] bench smoke (1024 clusters x 128 ticks)"
# prefer the attached accelerator; fall back to CPU if it is absent or hung.
# Artifact trail (ISSUE 10 satellite): a REAL bench round is recorded with
# `python bench.py --out` — auto-numbers the next BENCH_r<N>.json so the
# per-round trajectory (BENCH_r01..) stays machine-readable instead of
# living only in PERF.md prose; the smoke here deliberately does NOT write
# an artifact (smoke scale is not a round).
{ timeout 900 python bench.py 1024 128 \
  || MADTPU_BENCH_PLATFORM=cpu timeout 900 python bench.py 1024 128; } \
  | tee bench_smoke.out
# per-profile gate table (ISSUE 19): every storm_profiles() name must hold
# its clean-algorithm liveness floor + p99 ceiling (config.profile_gates(),
# the same table the gray smoke above checks two rows of) — a failing row
# names the profile and which side (liveness/p99/violations) breached.
python - <<'PY'
import json
doc = json.loads(open("bench_smoke.out").read().strip().splitlines()[-1])
pg = doc["detail"]["profile_gates"]
bad = {n: r for n, r in pg["profiles"].items() if not r["pass"]}
assert doc["detail"]["profile_gates_pass"], f"profile gate breach: {bad}"
print(f"profile gate table: {len(pg['profiles'])} profiles green "
      f"in {pg['wall_s']}s")
PY

echo "CI GREEN"
