"""On-chip safety soak: the north-star claim, measured, at 10x scale.

BASELINE.json sets the bar at ">=100k 5-node cluster-steps/s/chip with zero
safety violations per 1e9 cluster-steps". This tool runs >= 1e10 cluster-steps
on the attached accelerator — the flagship fuzz config, a harsher fault storm,
the 16-combo knob grid, and the kv / ctrler / shardkv service stacks — and records the
evidence as ``SOAK_r{N}.json``: total steps, violations (must be 0), liveness
counters, and throughput per region.

Each region is ONE compiled program re-invoked with a fresh seed per rep
(engine.make_fuzz_fn's seed is a runtime argument), so the soak covers
``reps x n_clusters`` distinct (seed, schedule) universes at full device
throughput. Any violation reports (seed, cluster_id) for exact replay via
``engine.replay_cluster`` / the differential bridge (bridge.py).

Usage:
    python _soak.py                   # full soak (~20 min on TPU v5e)
    python _soak.py 0.01              # scaled: 1% of the full step budget
    python _soak.py 1.0 500000        # fresh seed base: all-new universes
    SOAK_OUT=SOAK_r03.json python _soak.py
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.ctrler import CtrlerConfig, make_ctrler_fuzz_fn
from madraft_tpu.tpusim.engine import make_fuzz_fn, make_sweep_fn, report
from madraft_tpu.tpusim.kv import KvConfig, make_kv_fuzz_fn
from madraft_tpu.tpusim.shardkv import (
    ShardKvConfig,
    make_shardkv_fuzz_fn,
    shardkv_report,
)
from madraft_tpu.tpusim.telemetry import (
    HeartbeatWriter,
    digest_line,
    manifest_path,
    write_json_atomic,
)

# set by main(); module-level defaults keep `import _soak` (e.g. from
# _campaign.py, for the shared grid) argument-free
SCALE = 1.0
SEED_BASE = 0  # added to every region's seed0: re-runs cover fresh universes

# Live telemetry (ISSUE 17): main() rebinds this to a file-backed writer
# when SOAK_OUT is set (<SOAK_OUT>.heartbeat.jsonl + the attachable
# manifest, so `stats --follow` can watch a multi-day soak and a dead run
# reads as "crashed" instead of silence). The default pathless writer
# keeps `import _soak` and bare `drive()` calls file-free.
HEARTBEAT = HeartbeatWriter()


def flagship() -> SimConfig:
    return SimConfig(
        n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01,
        p_restart=0.2, max_dead=2, p_repartition=0.02, p_heal=0.05,
    )


def storm() -> SimConfig:
    # every fault class at once, including the round-3 targeted cuts
    return SimConfig(
        n_nodes=5, p_client_cmd=0.3, loss_prob=0.3, p_crash=0.02,
        p_restart=0.2, max_dead=2, p_repartition=0.05, p_heal=0.08,
        p_leader_part=0.01, p_asym_cut=0.03,
    )


# The 16-combo loss x crash x repartition grid shared with _campaign.py
# (single source so the soak and the campaign always sweep the same space).
GRID_COMBOS = [
    (l, c, r)
    for l in (0.0, 0.1, 0.3, 0.5)
    for c in (0.0, 0.02)
    for r in (0.0, 0.05)
]


def grid_knobs(cfg: SimConfig, n: int):
    """Per-cluster knobs tiling GRID_COMBOS across a batch of n clusters."""
    combos = GRID_COMBOS
    per = n // len(combos)
    reps = [per] * len(combos)
    reps[-1] += n - per * len(combos)
    loss = jnp.repeat(
        jnp.asarray([x[0] for x in combos], jnp.float32),
        jnp.asarray(reps), total_repeat_length=n,
    )
    crash = jnp.repeat(
        jnp.asarray([x[1] for x in combos], jnp.float32),
        jnp.asarray(reps), total_repeat_length=n,
    )
    rep_p = jnp.repeat(
        jnp.asarray([x[2] for x in combos], jnp.float32),
        jnp.asarray(reps), total_repeat_length=n,
    )
    return cfg.knobs()._replace(loss_prob=loss, p_crash=crash, p_repartition=rep_p)


def _checkpoint_partial(rows) -> None:
    """After each region, persist what has run so far: two tunnel outages
    this round killed soaks mid-run and left NO artifact for ~1e10 clean
    steps. Written atomically (telemetry.write_json_atomic — the one copy
    of the tmp+replace rule) so the abrupt kill this exists to survive
    cannot half-write it; replaced by the final artifact on success. The
    checkpoint references the run manifest and its last-generation pointer,
    so a recovery tool can line the partial up against the heartbeat
    stream's finer-grained per-rep rows."""
    path = os.environ.get("SOAK_OUT")
    if path:
        doc = {"regions": rows, "complete": False}
        if HEARTBEAT.path:
            doc["heartbeat_manifest"] = manifest_path(HEARTBEAT.path)
            doc["last_gen"] = HEARTBEAT.gen - 1 if HEARTBEAT.gen else None
        write_json_atomic(path + ".partial", doc)


def drive(name, fn, steps_per_rep, target_steps, stats, seed0):
    """Re-invoke fn(seed) until target_steps; return the region row.

    ``stats(final) -> (violation_array, live_count)`` is called once per rep.
    One warm-up rep (an extra seed, not counted) runs before the clock starts
    so XLA compilation never pollutes the recorded steps_per_sec.
    """
    seed0 += SEED_BASE
    reps = max(1, int(round(target_steps / steps_per_rep)))
    stats(fn(seed0 - 1))  # warm-up: compile + first run, excluded from timing
    t0 = time.perf_counter()
    viol = 0
    live = 0
    bad = []
    for r in range(reps):
        final = fn(seed0 + r)
        v, l = stats(final)
        viol += int((v != 0).sum())
        if (v != 0).any():
            bad.append({"seed": seed0 + r, "clusters": np.nonzero(v != 0)[0][:8].tolist()})
        live += int(l)
        # one heartbeat row per rep (ISSUE 17): the soak's per-rep progress
        # rides the same stream/manifest as the pool's per-generation rows,
        # so `stats --follow` and the watcher tri-state work unchanged
        w = time.perf_counter() - t0
        HEARTBEAT.row(
            {"region": name, "rep": r + 1, "reps": reps,
             "cluster_steps": (r + 1) * steps_per_rep,
             "violating": viol, "live": live},
            {"wall_s": round(w, 3),
             "steps_per_s": round((r + 1) * steps_per_rep / w, 1)
             if w > 0 else None,
             "budget_frac": round((r + 1) / reps, 4)},
        )
    wall = time.perf_counter() - t0
    row = {
        "region": name,
        "reps": reps,
        "cluster_steps": reps * steps_per_rep,
        "wall_s": round(wall, 1),
        "steps_per_sec": round(reps * steps_per_rep / wall, 1),
        "violating_clusters": viol,
        "live_clusters": live,
    }
    if bad:
        row["violations"] = bad[:16]
    print(json.dumps(row), flush=True)
    return row


def main() -> None:
    global SCALE, SEED_BASE
    if len(sys.argv) > 1:
        SCALE = float(sys.argv[1])
    if len(sys.argv) > 2:
        SEED_BASE = int(sys.argv[2])
    # Bounded retry/backoff before touching the backend: the tunnel fails by
    # hanging inside PJRT init (three outages in round 3), and a hung soak
    # leaves no artifact at all. SOAK_PLATFORM=cpu skips the probe (CI smoke).
    from madraft_tpu._platform import apply_platform, init_backend_with_retry

    # a soak exists to leave artifacts — opt in to TUNNEL_STATUS.jsonl
    # probe recording (library/test imports stay silent by default)
    os.environ.setdefault("MADTPU_TUNNEL_LOG", "1")
    plat = apply_platform(os.environ.get("SOAK_PLATFORM"))
    if plat != "cpu":
        ok, detail = init_backend_with_retry(plat, attempts=6)
        if not ok:
            sys.exit(f"soak: backend init failed after retries: {detail}")
    dev = str(jax.devices()[0])
    t_start = time.time()
    rows = []

    global HEARTBEAT
    soak_out = os.environ.get("SOAK_OUT")

    def on_row(row):
        # low-cadence human progress on stderr (every 10th rep), derived
        # from the same heartbeat rows the file stream carries — no second
        # progress bookkeeping path
        if row["gen"] % 10 == 0:
            det = row.get("det", {})
            print(f"# soak {det.get('region')}: {digest_line(row)}",
                  file=sys.stderr, flush=True)

    HEARTBEAT = HeartbeatWriter(
        soak_out + ".heartbeat.jsonl" if soak_out else None, on_row=on_row
    )
    HEARTBEAT.open({"kind": "soak", "scale": SCALE, "seed_base": SEED_BASE,
                    "device": dev, "out": soak_out})

    def run_region(*a, **kw):
        rows.append(drive(*a, **kw))
        _checkpoint_partial(rows)

    def raft_stats(f):
        return (np.asarray(f.violations),
                int((np.asarray(f.shadow_len) > 0).sum()))

    # --- raft flagship: ~6e9 steps -----------------------------------------
    nc, nt = 4096, 2048
    cfg = flagship()
    fn = make_fuzz_fn(cfg, nc, nt)
    run_region(
        "raft_flagship", fn, nc * nt, 6e9 * SCALE, raft_stats, seed0=1000,
    )

    # --- raft storm: ~2e9 steps --------------------------------------------
    fn = make_fuzz_fn(storm(), nc, nt)
    run_region(
        "raft_storm", fn, nc * nt, 2e9 * SCALE, raft_stats, seed0=2000,
    )

    # --- 7-node storm (topology diversity): ~1e9 steps ---------------------
    cfg7 = SimConfig(
        n_nodes=7, p_client_cmd=0.2, loss_prob=0.2, p_crash=0.02,
        p_restart=0.2, max_dead=3, p_repartition=0.04, p_heal=0.08,
        p_leader_part=0.01, p_asym_cut=0.02,
    )
    fn = make_fuzz_fn(cfg7, nc, nt)
    run_region(
        "raft_storm_7node", fn, nc * nt, 1e9 * SCALE, raft_stats, seed0=2500,
    )

    # --- knob grid (heterogeneous knobs, one program): ~1e9 steps ----------
    fn = make_sweep_fn(flagship(), grid_knobs(flagship(), nc), nc, nt)
    run_region(
        "raft_grid16", fn, nc * nt, 1e9 * SCALE, raft_stats, seed0=3000,
    )

    # --- kv service stack: ~5e8 steps --------------------------------------
    kcfg = flagship().replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16
    )
    nck, ntk = 1024, 1024
    fn = make_kv_fuzz_fn(kcfg, KvConfig(p_get=0.3, p_put=0.2), nck, ntk)
    run_region(
        "kv_fuzz", fn, nck * ntk, 5e8 * SCALE,
        lambda f: (np.asarray(f.raft.violations),
                   int((np.asarray(f.clerk_acked).sum(axis=-1) > 0).sum())),
        seed0=4000,
    )

    # --- ctrler (4A) service stack: ~2e8 steps ------------------------------
    ccfg = flagship().replace(
        p_client_cmd=0.0, compact_at_commit=False, log_cap=32, compact_every=8
    )
    fn = make_ctrler_fuzz_fn(ccfg, CtrlerConfig(), nck, ntk)
    run_region(
        "ctrler_fuzz", fn, nck * ntk, 2e8 * SCALE,
        lambda f: (np.asarray(f.raft.violations),
                   int((np.asarray(f.w_cfg_num) > 0).sum())),
        seed0=6000,
    )

    # --- shardkv service stack: ~2e8 group-cluster steps -------------------
    scfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05,
    )
    skcfg = ShardKvConfig(p_put=0.2)  # full op set: Get/Put/Append
    ncs, nts = 256, 512
    fn = make_shardkv_fuzz_fn(scfg, skcfg, ncs, nts)

    def skv_stats(f):
        r = shardkv_report(f)  # service-level AND per-group raft violations
        return r.violations | r.raft_violations, int(r.installs.sum())

    run_region(
        "shardkv_fuzz", fn, ncs * nts * skcfg.n_groups, 2e8 * SCALE,
        skv_stats, seed0=5000,
    )

    # --- shardkv with the LIVE on-device controller: ~1e8 steps -----------
    lkcfg = ShardKvConfig(p_put=0.2, live_ctrler=True, p_phantom=0.4,
                          cfg_interval=40)
    fn = make_shardkv_fuzz_fn(scfg, lkcfg, ncs, nts)
    run_region(
        "shardkv_live_ctrler", fn,
        ncs * nts * (lkcfg.n_groups + 1),  # +1: the ctrler cluster ticks too
        1e8 * SCALE, skv_stats, seed0=5500,
    )

    # --- shardkv with the COMPUTED controller (4A∘4B): ~1e8 steps ---------
    # config content computed by the per-replica 4A rebalance from committed
    # membership flips; the composite adopted-vs-canonical oracle is armed
    ckcfg = ShardKvConfig(p_put=0.2, computed_ctrler=True, p_phantom=0.4,
                          cfg_interval=40)
    fn = make_shardkv_fuzz_fn(scfg, ckcfg, ncs, nts)
    run_region(
        "shardkv_computed_ctrler", fn,
        ncs * nts * (ckcfg.n_groups + 1),
        1e8 * SCALE, skv_stats, seed0=5800,
    )

    total = sum(r["cluster_steps"] for r in rows)
    viol = sum(r["violating_clusters"] for r in rows)
    out = {
        "metric": "soak_cluster_steps_zero_violations",
        "total_cluster_steps": total,
        "violating_clusters": viol,
        "wall_s": round(time.time() - t_start, 1),
        "device": dev,
        "scale": SCALE,
        "seed_base": SEED_BASE,
        "regions": rows,
    }
    path = os.environ.get("SOAK_OUT")
    if path:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        partial = path + ".partial"
        if os.path.exists(partial):
            os.unlink(partial)
    # terminal manifest: watchers see "done" (a violating soak still RAN to
    # completion — the artifact carries the verdict; an abrupt kill instead
    # reads as "crashed": running status with a dead pid)
    HEARTBEAT.close("done")
    print(json.dumps(out), flush=True)
    sys.exit(1 if viol else 0)


if __name__ == "__main__":
    main()
