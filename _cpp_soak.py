"""C++ seed-breadth soak: the madsim MADSIM_TEST_NUM idiom at real breadth.

The reference's workflow is many-seed reruns of the full suite
(/root/reference/README.md:54-87: MADSIM_TEST_NUM reruns with derived
seeds, MADSIM_TEST_CHECK_DETERMINISTIC double-runs). CI covers 2 seeds
(ci.sh); this tool runs the full 70-test C++ suite across N seeds — each
seed under the determinism double-run (every test executes twice and the
trace hashes must match) — and records the evidence as an artifact the
same shape as the TPU soak's regions.

Usage:
    python _cpp_soak.py [n_seeds] [seed_base]     # default 50 seeds from 7000
    SOAK_OUT=SOAK_r04_cpp.json python _cpp_soak.py
"""

import json
import os
import re
import subprocess
import sys
import time


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    seed_base = int(sys.argv[2]) if len(sys.argv) > 2 else 7000
    here = os.path.dirname(os.path.abspath(__file__))
    binary = os.path.join(here, "build", "madtpu_tests")
    if not os.path.exists(binary):
        sys.exit(f"build first: cmake -S cpp -B build -G Ninja && ninja -C build")

    t0 = time.time()
    failed = []
    tests_per_seed = 0
    for i in range(n_seeds):
        seed = seed_base + i
        env = dict(
            os.environ,
            MADTPU_TEST_SEED=str(seed),
            MADTPU_TEST_CHECK_DETERMINISTIC="1",
        )
        try:
            proc = subprocess.run(
                [binary], env=env, capture_output=True, text=True,
                timeout=600,
            )
        except subprocess.TimeoutExpired:
            # a hung seed must cost one row, not the whole artifact (the
            # same leaves-no-evidence failure mode the TPU soak's per-region
            # checkpointing closed in round 3)
            failed.append({"seed": seed, "rc": "timeout", "tail": []})
            print(json.dumps(failed[-1]), flush=True)
            continue
        # the runner prints one "[ OK ]" line per test execution (each test
        # runs twice under the determinism check) and no summary line;
        # failures exit nonzero with a FAIL/hash-mismatch line
        oks = len(re.findall(r"^\[ OK", proc.stdout, re.M))
        bad = re.findall(
            r"^.*(?:FAIL|mismatch|panic|WDOG).*$", proc.stdout + proc.stderr,
            re.M,
        )
        if proc.returncode != 0 or bad:
            tail = (bad or proc.stdout.strip().splitlines()[-1:])[:3]
            row = {"seed": seed, "rc": proc.returncode, "tail": tail}
            # the in-sim watchdog names the wedged test and its virtual time
            # (so a hang is a localized finding, not an empty-tail mystery)
            m = re.search(
                r"\[WDOG \] test (\S+) exceeded .*?"
                r"\(real ([0-9.]+)s, virtual ([0-9.]+)s\)",
                proc.stderr,
            )
            if m:
                row["test"] = m.group(1)
                row["real_time_s"] = float(m.group(2))
                row["virt_time_s"] = float(m.group(3))
            else:
                # SIGALRM backstop (CPU-bound hang): test name only
                m2 = re.search(r"\[WDOG \] test (\S+) hit the SIGALRM",
                               proc.stderr)
                if m2:
                    row["test"] = m2.group(1)
            failed.append(row)
            print(json.dumps(failed[-1]), flush=True)
        else:
            tests_per_seed = max(tests_per_seed, oks // 2)
        if (i + 1) % 10 == 0:
            print(
                f"# {i + 1}/{n_seeds} seeds, {len(failed)} failed, "
                f"{time.time() - t0:.0f}s",
                file=sys.stderr, flush=True,
            )

    out = {
        "metric": "cpp_suite_seed_soak",
        "region": "cpp_seeds",
        "n_seeds": n_seeds,
        "seed_base": seed_base,
        "tests_per_seed": tests_per_seed,
        "deterministic_double_run": True,
        "failed_seeds": failed,
        "wall_s": round(time.time() - t0, 1),
    }
    path = os.environ.get("SOAK_OUT")
    if path:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
