"""C++ seed-breadth soak: the madsim MADSIM_TEST_NUM idiom at real breadth.

The reference's workflow is many-seed reruns of the full suite
(/root/reference/README.md:54-87: MADSIM_TEST_NUM reruns with derived
seeds, MADSIM_TEST_CHECK_DETERMINISTIC double-runs). CI covers 2 seeds
(ci.sh); this tool runs the full 70-test C++ suite across N seeds — each
seed under the determinism double-run (every test executes twice and the
trace hashes must match) — and records the evidence as an artifact the
same shape as the TPU soak's regions.

Usage:
    python _cpp_soak.py [n_seeds] [seed_base]     # default 50 seeds from 7000
    SOAK_OUT=SOAK_r04_cpp.json python _cpp_soak.py
"""

import json
import os
import re
import subprocess
import sys
import time

# Load telemetry.py by file path, NOT through the madraft_tpu package
# (whose __init__ imports the JAX stack): this tool must keep running on
# a box with no JAX at all, and telemetry.py itself is stdlib-only at
# module scope by contract.
import importlib.util as _ilu

_spec = _ilu.spec_from_file_location(
    "_madtpu_telemetry",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "madraft_tpu", "tpusim", "telemetry.py"),
)
_telemetry = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_telemetry)
HeartbeatWriter = _telemetry.HeartbeatWriter
digest_line = _telemetry.digest_line


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    seed_base = int(sys.argv[2]) if len(sys.argv) > 2 else 7000
    here = os.path.dirname(os.path.abspath(__file__))
    binary = os.path.join(here, "build", "madtpu_tests")
    if not os.path.exists(binary):
        sys.exit(f"build first: cmake -S cpp -B build -G Ninja && ninja -C build")

    # progress rides the heartbeat stream + manifest (ISSUE 17), same as
    # the TPU soak: one row per seed, stderr digest every 10th, and a
    # watcher can tell crashed from running from done via the manifest
    soak_out = os.environ.get("SOAK_OUT")

    def on_row(row):
        if row["gen"] % 10 == 0:
            print(f"# cpp_seeds: {digest_line(row)}", file=sys.stderr,
                  flush=True)

    hb = HeartbeatWriter(
        soak_out + ".heartbeat.jsonl" if soak_out else None, on_row=on_row
    )
    hb.open({"kind": "cpp_soak", "n_seeds": n_seeds,
             "seed_base": seed_base, "out": soak_out})

    t0 = time.time()
    failed = []
    tests_per_seed = 0
    for i in range(n_seeds):
        seed = seed_base + i
        env = dict(
            os.environ,
            MADTPU_TEST_SEED=str(seed),
            MADTPU_TEST_CHECK_DETERMINISTIC="1",
        )
        try:
            proc = subprocess.run(
                [binary], env=env, capture_output=True, text=True,
                timeout=600,
            )
        except subprocess.TimeoutExpired:
            # a hung seed must cost one row, not the whole artifact (the
            # same leaves-no-evidence failure mode the TPU soak's per-region
            # checkpointing closed in round 3)
            failed.append({"seed": seed, "rc": "timeout", "tail": []})
            print(json.dumps(failed[-1]), flush=True)
            continue
        # the runner prints one "[ OK ]" line per test execution (each test
        # runs twice under the determinism check) and no summary line;
        # failures exit nonzero with a FAIL/hash-mismatch line
        oks = len(re.findall(r"^\[ OK", proc.stdout, re.M))
        bad = re.findall(
            r"^.*(?:FAIL|mismatch|panic|WDOG).*$", proc.stdout + proc.stderr,
            re.M,
        )
        if proc.returncode != 0 or bad:
            tail = (bad or proc.stdout.strip().splitlines()[-1:])[:3]
            row = {"seed": seed, "rc": proc.returncode, "tail": tail}
            # the in-sim watchdog names the wedged test and its virtual time
            # (so a hang is a localized finding, not an empty-tail mystery)
            m = re.search(
                r"\[WDOG \] test (\S+) exceeded .*?"
                r"\(real ([0-9.]+)s, virtual ([0-9.]+)s\)",
                proc.stderr,
            )
            if m:
                row["test"] = m.group(1)
                row["real_time_s"] = float(m.group(2))
                row["virt_time_s"] = float(m.group(3))
            else:
                # SIGALRM backstop (CPU-bound hang): test name only
                m2 = re.search(r"\[WDOG \] test (\S+) hit the SIGALRM",
                               proc.stderr)
                if m2:
                    row["test"] = m2.group(1)
            failed.append(row)
            print(json.dumps(failed[-1]), flush=True)
        else:
            tests_per_seed = max(tests_per_seed, oks // 2)
        w = time.time() - t0
        hb.row(
            {"seed": seed, "seeds_run": i + 1, "n_seeds": n_seeds,
             "failed": len(failed)},
            {"wall_s": round(w, 1),
             "budget_frac": round((i + 1) / n_seeds, 4)},
        )

    out = {
        "metric": "cpp_suite_seed_soak",
        "region": "cpp_seeds",
        "n_seeds": n_seeds,
        "seed_base": seed_base,
        "tests_per_seed": tests_per_seed,
        "deterministic_double_run": True,
        "failed_seeds": failed,
        "wall_s": round(time.time() - t0, 1),
    }
    path = os.environ.get("SOAK_OUT")
    if path:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    hb.close("done")  # a failing seed still ran to completion
    print(json.dumps(out), flush=True)
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
