"""Config-space fuzz campaign: safety + liveness across the knob and shape
grid (the wide-net companion to the targeted test suite; ~6 min on CPU).

Covers: a 16-combo loss x crash x repartition sweep in ONE compiled program;
raft shape corners (3/4/5/7 nodes, ae_max 1..8, log_cap 32..128,
compact_every 1..48, leader-targeted + asymmetric cuts); kv extremes
(apply_max=1 backlog, 8 hot clients on 2 keys); ctrler extremes (hot clerks,
wide gid universe, query-heavy, starved walker); service sweeps
(make_*_sweep_fn: a kv workload x loss grid and a half-bugged ctrler batch
whose violations must localize exactly); shardkv topologies
(2..4 groups, 4..10 shards, 3..5 nodes/group). Exits non-zero on any
violation OR liveness anomaly (a config that stops committing / stalls its
schedule), which is how round 3's response-starvation and GC-leak bugs were
found. Usage: python _campaign.py  (set MADTPU_PLATFORM to override the
backend; defaults to CPU — the point is breadth, not throughput).
"""
import os
import time

import jax

jax.config.update("jax_platforms", os.environ.get("MADTPU_PLATFORM", "cpu"))
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz, make_sweep_fn, report
from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz
from madraft_tpu.tpusim.shardkv import ShardKvConfig, shardkv_fuzz

t0 = time.time()
fails = []

def check(name, ok, detail=""):
    print(f"[{time.time()-t0:6.0f}s] {'OK ' if ok else 'FAIL'} {name} {detail}", flush=True)
    if not ok: fails.append(name)

# 1. knob grid in one program: loss x crash x repartition (grid shared with
# _soak.py so the campaign and the on-chip soak sweep the same space)
from _soak import GRID_COMBOS as combos, grid_knobs

base = SimConfig(n_nodes=5, p_client_cmd=0.2, p_restart=0.2, max_dead=2, p_heal=0.05)
per = 24
n = len(combos) * per
r = report(make_sweep_fn(base, grid_knobs(base, n), n, 1024)(77))
check("grid 16-combo sweep", r.n_violating == 0, f"viol={r.n_violating}")
for i, (l, c, rp) in enumerate(combos):
    com = r.committed[i*per:(i+1)*per]
    if l <= 0.3:
        check(f"  liveness loss={l} crash={c} rep={rp}", (com > 0).all(),
              f"commit0={int((com==0).sum())}/{per} mean={com.mean():.0f}")

# 2. shape corners
for cfg, ticks in [
    (SimConfig(n_nodes=3, p_client_cmd=0.3, loss_prob=0.2, p_crash=0.02, p_restart=0.2, max_dead=1), 1024),
    (SimConfig(n_nodes=7, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.02, p_restart=0.2, max_dead=3, p_repartition=0.03, p_heal=0.06), 768),
    (SimConfig(n_nodes=5, ae_max=1, p_client_cmd=0.3, loss_prob=0.1), 768),
    (SimConfig(n_nodes=5, ae_max=8, p_client_cmd=0.4, loss_prob=0.1, p_crash=0.02, p_restart=0.2, max_dead=2), 768),
    (SimConfig(n_nodes=5, log_cap=32, compact_every=4, p_client_cmd=0.3, loss_prob=0.1, p_crash=0.02, p_restart=0.2, max_dead=2), 768),
    (SimConfig(n_nodes=5, log_cap=128, compact_every=48, p_client_cmd=0.4, loss_prob=0.1), 768),
    (SimConfig(n_nodes=5, compact_every=1, p_client_cmd=0.3, loss_prob=0.15, p_crash=0.02, p_restart=0.2, max_dead=2), 768),
    (SimConfig(n_nodes=4, p_client_cmd=0.2, loss_prob=0.2, p_leader_part=0.03, p_asym_cut=0.08, p_heal=0.05), 768),
]:
    rr = fuzz(cfg, seed=88, n_clusters=48, n_ticks=ticks)
    tag = f"n={cfg.n_nodes} ae={cfg.ae_max} cap={cfg.log_cap} ce={cfg.compact_every}"
    check(f"shape {tag}", rr.n_violating == 0, f"viol={rr.n_violating} commit_mean={rr.committed.mean():.0f}")
    check(f"  live {tag}", (rr.committed > 0).all(), f"zero={int((rr.committed==0).sum())}")

# 3. kv extremes
kcfg_base = SimConfig(n_nodes=5, p_client_cmd=0.0, compact_at_commit=False,
                      log_cap=64, compact_every=16, loss_prob=0.15,
                      p_crash=0.02, p_restart=0.2, max_dead=2, p_repartition=0.03, p_heal=0.06)
for kv, ticks in [
    (KvConfig(apply_max=1, p_retry=1.0, p_get=0.5), 768),
    (KvConfig(n_clients=8, n_keys=2, p_op=0.8, p_retry=0.9, p_get=0.4), 768),
    (KvConfig(p_op=0.6, p_retry=0.8, p_get=0.3, p_put=0.4), 768),
]:
    rr = kv_fuzz(kcfg_base, kv, seed=88, n_clusters=32, n_ticks=ticks)
    check(f"kv nc={kv.n_clients} am={kv.apply_max}", rr.n_violating == 0,
          f"viol={rr.n_violating} acked={rr.acked_ops.mean():.0f}")

# 3b. ctrler (4A) extremes: many hot clerks churning tiny config histories,
# a wide gid universe, and a query-heavy mix
from madraft_tpu.tpusim.ctrler import CtrlerConfig, ctrler_fuzz

ccfg_base = kcfg_base.replace(log_cap=32, compact_every=8)
for ct, ticks in [
    (CtrlerConfig(n_clients=8, n_configs=12, p_op=0.8, p_retry=0.9), 768),
    (CtrlerConfig(n_gids=10, p_move=0.3, p_query=0.1), 768),
    # walk_max must outpace the dup-entry commit rate (p_retry=1.0 appends a
    # dup per blocked clerk per tick) or the walker legitimately falls out of
    # the shadow window — 4/tick covers the 4-clerk worst case
    (CtrlerConfig(apply_max=1, walk_max=4, p_retry=1.0, p_query=0.5), 768),
]:
    rr = ctrler_fuzz(ccfg_base, ct, seed=88, n_clusters=32, n_ticks=ticks)
    check(f"ctrler ng={ct.n_gids} nc={ct.n_clients} am={ct.apply_max}",
          rr.n_violating == 0,
          f"viol={rr.n_violating} cfgs={rr.configs_created.mean():.0f} "
          f"q={rr.queries_done.mean():.0f}")
    check(f"  progress ng={ct.n_gids} nc={ct.n_clients} am={ct.apply_max}",
          (rr.configs_created > 0).all() and rr.queries_done.sum() > 0,
          f"cfg0={int((rr.configs_created == 0).sum())}")

# 3c. service sweeps: heterogeneous per-cluster knob matrices in one program
# (the make_*_sweep_fn surface) — a workload x loss grid on kv and a
# half-bugged ctrler batch whose violations must localize exactly
from madraft_tpu.tpusim.ctrler import make_ctrler_sweep_fn, ctrler_report
from madraft_tpu.tpusim.kv import make_kv_sweep_fn, kv_report

n_sw = 64
cell4 = np.arange(n_sw) // (n_sw // 4)
kv_kn = kcfg_base.knobs()._replace(
    loss_prob=jnp.asarray([0.0, 0.0, 0.3, 0.3], jnp.float32)[cell4])
kv_skn = KvConfig().knobs()._replace(
    p_get=jnp.asarray([0.0, 0.5, 0.0, 0.5], jnp.float32)[cell4])
rr = kv_report(make_kv_sweep_fn(kcfg_base, kv_kn, kv_skn, KvConfig(),
                                n_sw, 512)(99))
lossless_acked = rr.acked_ops[cell4 < 2]
check("kv sweep 2x2 loss x p_get", rr.n_violating == 0,
      f"viol={rr.n_violating} acked={rr.acked_ops.mean():.0f}")
check("  kv sweep liveness (lossless cells)", (lossless_acked > 0).all(),
      f"zero={int((lossless_acked == 0).sum())}/{lossless_acked.size}")

bugged = np.arange(n_sw) < n_sw // 2
ct_skn = CtrlerConfig().knobs()._replace(
    bug_greedy_rebalance=jnp.asarray(bugged))
rr = ctrler_report(make_ctrler_sweep_fn(
    ccfg_base, ccfg_base.knobs(), ct_skn, CtrlerConfig(), n_sw, 512)(99))
vio = rr.violations != 0
check("ctrler sweep bug localization",
      bool(vio[bugged].any() and not vio[~bugged].any()),
      f"bugged={int(vio[bugged].sum())} clean={int(vio[~bugged].sum())}")
check("  ctrler sweep liveness", (rr.configs_created > 0).all(),
      f"cfg0={int((rr.configs_created == 0).sum())}")

# 4. shardkv shapes
for g, ns, nodes in [(2, 4, 3), (4, 10, 3), (3, 10, 5)]:
    raft = SimConfig(n_nodes=nodes, p_client_cmd=0.0, compact_at_commit=False,
                     log_cap=64, compact_every=16, loss_prob=0.1,
                     p_crash=0.01, p_restart=0.2, max_dead=1)
    sk = ShardKvConfig(n_groups=g, n_shards=ns, n_configs=10, cfg_interval=60, p_get=0.3, p_put=0.2)
    rr = shardkv_fuzz(raft, sk, seed=88, n_clusters=10, n_ticks=1100)
    check(f"shardkv g={g} ns={ns} n={nodes}", rr.n_violating == 0,
          f"viol={rr.n_violating} cfg_min={rr.final_cfg.min()} inst={rr.installs.sum()} del={rr.deletes.sum()}")
    check(f"  progress g={g} ns={ns}", (rr.final_cfg >= sk.n_configs - 3).all(),
          f"final={np.sort(rr.final_cfg).tolist()}")

# 5. shardkv with the LIVE on-device controller (announce/query protocol
# under a storm; shape-varied). Safety must hold and announces must resolve.
for g, nodes in [(2, 3), (3, 5)]:
    raft = SimConfig(n_nodes=nodes, p_client_cmd=0.0, compact_at_commit=False,
                     log_cap=64, compact_every=16, loss_prob=0.1,
                     p_crash=0.01, p_restart=0.2, max_dead=1,
                     p_repartition=0.03, p_heal=0.08)
    sk = ShardKvConfig(n_groups=g, n_configs=8, cfg_interval=45,
                       p_get=0.3, p_put=0.2, live_ctrler=True, p_phantom=0.4)
    rr = shardkv_fuzz(raft, sk, seed=91, n_clusters=10, n_ticks=900)
    check(f"shardkv live-ctrler g={g} n={nodes}", rr.n_violating == 0,
          f"viol={rr.n_violating} ann={rr.ann_resolved.min()}")
    check(f"  live announces resolve g={g}", (rr.ann_resolved >= 3).all(),
          f"ann={np.sort(rr.ann_resolved).tolist()}")
    check(f"  live walker never stalls g={g}",
          not rr.ctrl_walker_stalled.any(), "ctrl walker fell behind")

# 6. shardkv with the COMPUTED controller (the 4A∘4B composition): config
# content computed per replica from committed membership flips, under the
# same storm, shape-varied. Safety + slot resolution + the composite bug.
for g, nodes in [(2, 3), (3, 5)]:
    raft = SimConfig(n_nodes=nodes, p_client_cmd=0.0, compact_at_commit=False,
                     log_cap=64, compact_every=16, loss_prob=0.1,
                     p_crash=0.01, p_restart=0.2, max_dead=1,
                     p_repartition=0.03, p_heal=0.08)
    sk = ShardKvConfig(n_groups=g, n_configs=8, cfg_interval=45,
                       p_get=0.3, p_put=0.2, computed_ctrler=True,
                       p_phantom=0.4)
    rr = shardkv_fuzz(raft, sk, seed=93, n_clusters=10, n_ticks=900)
    check(f"shardkv computed-ctrler g={g} n={nodes}", rr.n_violating == 0,
          f"viol={rr.n_violating} ann={rr.ann_resolved.min()}")
    check(f"  computed slots resolve g={g}", (rr.ann_resolved >= 3).all(),
          f"slots={np.sort(rr.ann_resolved).tolist()}")
from madraft_tpu.tpusim.shardkv import VIOLATION_SHARD_CTRL_STALE

raft3 = SimConfig(n_nodes=3, p_client_cmd=0.0, compact_at_commit=False,
                  log_cap=64, compact_every=16, loss_prob=0.05)
skb = ShardKvConfig(computed_ctrler=True, bug_rotate_tiebreak=True,
                    cfg_interval=40)
rr = shardkv_fuzz(raft3, skb, seed=7, n_clusters=12, n_ticks=512)
check("shardkv composite rotate bug caught",
      ((rr.violations & VIOLATION_SHARD_CTRL_STALE) != 0).any(),
      "the 4A rotate bug never propagated to a 4B violation")

print("CAMPAIGN DONE", "FAILURES:" if fails else "all clean", fails)
raise SystemExit(1 if fails else 0)
