"""Megakernel ceiling probe: turn PERF.md's "~2x upside bound" into a
measured number, settling the whole-tick-Pallas question (round-3 verdict
item 7).

Any tick implementation — XLA-fused phases or a single hand-written Pallas
megakernel — must at minimum read and write the whole cluster state once
per tick (the mandatory-traffic floor; PERF.md "Roofline position"). This
probe times exactly that floor: a one-pass elementwise traversal of the
REAL flagship state pytree at the bench batch size, loop-inside-jit with
donated buffers (the PERF.md tunnel methodology — one device call runs
many passes so the ~63 ms tunnel latency amortizes away).

The implied ceiling is `passes/s x clusters`: the step rate of a
hypothetical tick that does nothing but the mandatory traffic at the
bandwidth this chip actually grants us. If that ceiling is ~2x the real
step rate (bench.py), a whole-tick megakernel — which must ALSO do the
tick's arithmetic, PRNG, and oracle reductions inside the same pass —
cannot reach even 2x, and the perf chapter closes with a measured number
instead of an estimate.

Usage (on the real chip): python _mega_probe.py [clusters] [passes]
Prints one JSON line.
"""

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig, init_cluster, step_cluster


def flagship() -> SimConfig:
    return SimConfig(
        n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01,
        p_restart=0.2, max_dead=2, p_repartition=0.02, p_heal=0.05,
    )


def touch(x):
    """Elementwise read-modify-write that XLA cannot elide or constant-fold
    across iterations (the scan carry makes each pass depend on the last)."""
    if x.dtype == jnp.bool_:
        return ~x
    return x + jnp.ones((), x.dtype)


def main() -> None:
    n_clusters = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    passes = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    cfg = flagship()
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
        jnp.arange(n_clusters)
    )
    states = jax.vmap(functools.partial(init_cluster, cfg))(keys)
    state_bytes = sum(x.nbytes for x in jax.tree.leaves(states))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def floor_pass(states):
        def body(c, _):
            return jax.tree.map(touch, c), None

        out, _ = jax.lax.scan(body, states, None, length=passes)
        return out

    out = floor_pass(states)
    _ = np.asarray(out.tick)  # sync
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        out = floor_pass(out)
        _ = np.asarray(out.tick)
        best = min(best, time.perf_counter() - t0)
    gbps = 2 * state_bytes * passes / best / 1e9
    ceiling = n_clusters * passes / best

    # the real tick, same process, same methodology (direct comparison)
    @functools.partial(jax.jit, donate_argnums=(0,))
    def real_ticks(states):
        def body(c, _):
            return jax.vmap(functools.partial(step_cluster, cfg))(c, keys), None

        out, _ = jax.lax.scan(body, states, None, length=passes)
        return out

    states2 = jax.vmap(functools.partial(init_cluster, cfg))(keys)
    out2 = real_ticks(states2)
    _ = np.asarray(out2.violations)
    best2 = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        out2 = real_ticks(out2)
        _ = np.asarray(out2.violations)
        best2 = min(best2, time.perf_counter() - t0)
    real = n_clusters * passes / best2

    print(json.dumps({
        "metric": "megakernel_ceiling_steps_per_sec",
        "value": round(ceiling, 1),
        "unit": "cluster-steps/s/chip",
        "detail": {
            "floor_pass_gbps": round(gbps, 1),
            "state_bytes_per_cluster": state_bytes // n_clusters,
            "real_steps_per_sec": round(real, 1),
            "ceiling_over_real": round(ceiling / real, 2),
            "n_clusters": n_clusters,
            "passes": passes,
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
