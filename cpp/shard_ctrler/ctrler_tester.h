// Shard-ctrler tester — the C++ analogue of the reference's minimal 4A
// harness (SURVEY.md §2 C14, /root/reference/src/shard_ctrler/tester.rs):
// start/shutdown servers, leader probe, and the config checker `check`:
// expected membership, no orphan shards, balance max ≤ min+1
// (tester.rs:113-150). No partitioning verbs in this lab.
#pragma once

#include <cstdio>
#include <memory>

#include "../tests/framework.h"
#include "ctrler.h"

namespace shard_ctrler {

using simcore::make_addr;
using simcore::MSEC;
using simcore::SEC;

class CtrlerTester {
 public:
  CtrlerTester(Sim* sim, int n, bool unreliable) : sim_(sim), n_(n) {
    for (int i = 0; i < n; i++) addrs_.push_back(make_addr(0, 0, 1, i + 1));
    servers_.resize(n);
    if (unreliable) {
      auto& cfg = sim_->net_config();
      cfg.packet_loss_rate = 0.1;
      cfg.send_latency_min = 1 * MSEC;
      cfg.send_latency_max = 27 * MSEC;
    }
    start_time_ = sim->now();
  }

  Task<void> init() {
    for (int i = 0; i < n_; i++) co_await sim_->spawn(start_server(i));
  }

  Sim* sim() { return sim_; }

  Task<void> start_server(int i) {  // tester.rs:74-80
    servers_[i] = co_await sim_->spawn(
        addrs_[i], ShardCtrler::boot(sim_, addrs_, i, std::nullopt));
  }
  void shutdown_server(int i) {  // tester.rs:66-70
    sim_->kill(addrs_[i]);
    servers_[i] = nullptr;
  }

  std::optional<int> leader() const {  // tester.rs:82-92
    for (int i = 0; i < n_; i++)
      if (servers_[i] && servers_[i]->is_leader()) return i;
    return std::nullopt;
  }

  CtrlerClerk make_client() {
    return CtrlerClerk(sim_, addrs_, next_client_++);
  }

  // tester.rs:113-150
  static Task<void> check(CtrlerClerk& ck, std::vector<Gid> gids) {
    Config c = co_await ck.query();
    MT_ASSERT_EQ(c.groups.size(), gids.size());
    for (Gid g : gids) {
      if (!c.groups.count(g)) {
        std::fprintf(stderr, "check: missing group %llu\n",
                     (unsigned long long)g);
        std::abort();
      }
    }
    // stronger than the reference (tester.rs:122-130, empty-groups only):
    // every shard's owner must always be a live group, or 0 when none exist
    for (size_t s = 0; s < N_SHARDS; s++) {
      Gid g = c.shards[s];
      bool ok = c.groups.empty() ? g == 0 : c.groups.count(g) > 0;
      if (!ok) {
        std::fprintf(stderr, "check: shard %zu -> invalid group %llu\n", s,
                     (unsigned long long)g);
        std::abort();
      }
    }
    if (!c.groups.empty()) {
      std::map<Gid, size_t> counts;
      for (Gid g : c.shards) counts[g]++;
      size_t mn = N_SHARDS + 1, mx = 0;
      for (auto& [gid, _] : c.groups) {
        size_t cnt = counts.count(gid) ? counts[gid] : 0;
        mn = std::min(mn, cnt);
        mx = std::max(mx, cnt);
      }
      if (mx > mn + 1) {
        std::fprintf(stderr, "check: imbalanced sharding, max %zu min %zu\n",
                     mx, mn);
        std::abort();
      }
    }
  }

  void end() const {
    std::printf("  ... elapsed %.2fs(virt) peers %d rpcs %llu\n",
                (sim_->now() - start_time_) / 1e9, n_,
                (unsigned long long)(sim_->msg_count() / 2));
  }

 private:
  Sim* sim_;
  int n_;
  uint64_t start_time_;
  std::vector<Addr> addrs_;
  std::vector<std::shared_ptr<ShardCtrler>> servers_;
  uint64_t next_client_ = 0;
};

}  // namespace shard_ctrler
