// shard_ctrler — the Lab 4A replicated configuration service on the generic
// RSM layer (SURVEY.md §2 C8, /root/reference/src/shard_ctrler/):
//   N_SHARDS = 10                      (mod.rs:9)
//   Config{num, shards: [Gid;10], groups: gid -> servers}   (msg.rs:10-18)
//   Op::{Query{num}, Join{groups}, Leave{gids}, Move{shard,gid}} (msg.rs:20-37)
//   Output = Option<Config>            (server.rs:14)
//   Clerk::{query, query_at, join, leave, move_}            (client.rs:16-34)
//
// Rebalancing on Join/Leave must be balanced (max−min ≤ 1 across groups),
// move as few shards as possible, and be deterministic across replicas —
// all containers here are ordered (std::map), never hash-ordered
// (reference README.md:79 bans order-dependent HashMap iteration).
#pragma once

#include <algorithm>
#include <array>
#include <cstdlib>
#include <cstring>
#include <optional>

#include "../kvraft/rsm.h"

namespace shard_ctrler {

using kvraft::ClerkCore;
using kvraft::RsmServer;
using raftcore::Dec;
using raftcore::Enc;
using simcore::Addr;
using simcore::Sim;
using simcore::Task;

constexpr size_t N_SHARDS = 10;  // mod.rs:9
using Gid = uint64_t;
constexpr uint64_t LATEST = ~0ull;  // Query{u64::MAX} = latest (client.rs:17)

// Deliberate-bug injection for the TPU<->C++ differential bridge, mirroring
// the batched backend's 4A planted bugs (madraft_tpu/tpusim/ctrler.py): the
// TPU fuzzer finds a violation under one of its rebalance bug modes; the C++
// replay (cpp/tools/ctrler_replay_core.h) runs the SAME protocol bug so the
// violation class must reproduce. Env-gated so the production path is
// untouched. Name table shared with the replay parser via bug_mode_of.
//   MADTPU_CTRLER_BUG=rotate_tiebreak   — tie-break order rotated by
//       MADTPU_CTRLER_ROT (per-replica): replicas diverge, the
//       HashMap-iteration-order classic the reference README warns about
//   MADTPU_CTRLER_BUG=greedy_rebalance  — all orphans to the one
//       least-loaded group, no balancing pass (balance breaks)
//   MADTPU_CTRLER_BUG=full_reshuffle    — balanced round-robin reassignment
//       ignoring retention (minimality breaks)
inline int ctrl_bug_mode_of(const char* name) {
  if (!name) return 0;
  if (!std::strcmp(name, "rotate_tiebreak")) return 1;
  if (!std::strcmp(name, "greedy_rebalance")) return 2;
  if (!std::strcmp(name, "full_reshuffle")) return 3;
  return 0;
}

inline bool is_known_ctrler_bug(const std::string& name) {
  return name == "none" || ctrl_bug_mode_of(name.c_str()) != 0;
}

inline int ctrl_bug_mode() {  // per call, not cached (capi multi-replay)
  return ctrl_bug_mode_of(std::getenv("MADTPU_CTRLER_BUG"));
}

inline uint64_t ctrl_rot() {
  const char* e = std::getenv("MADTPU_CTRLER_ROT");
  return e ? uint64_t(std::strtoull(e, nullptr, 10)) : 0;
}

struct Config {
  uint64_t num = 0;
  std::array<Gid, N_SHARDS> shards{};          // shard -> gid (0 = unassigned)
  std::map<Gid, std::vector<Addr>> groups;     // gid -> servers
  // non-aggregate on purpose — see the gcc-12 note in kvraft/rsm.h (std::map
  // headers are self-referential, bitwise relocation corrupts them)
  Config() = default;
  bool operator==(const Config& o) const {
    return num == o.num && shards == o.shards && groups == o.groups;
  }

  static void enc(Enc& e, const Config& c) {
    e.u64(c.num);
    for (auto g : c.shards) e.u64(g);
    e.u64(c.groups.size());
    for (auto& [gid, srvs] : c.groups) {
      e.u64(gid);
      e.u64(srvs.size());
      for (auto a : srvs) e.u64(a);
    }
  }
  static Config dec(Dec& d) {
    Config c;
    c.num = d.u64();
    for (auto& g : c.shards) g = d.u64();
    uint64_t ng = d.u64();
    for (uint64_t i = 0; i < ng; i++) {
      Gid gid = d.u64();
      auto& srvs = c.groups[gid];
      uint64_t ns = d.u64();
      for (uint64_t j = 0; j < ns; j++) srvs.push_back(Addr(d.u64()));
    }
    return c;
  }
};

struct CtrlOp {
  enum class Kind : uint8_t { Query, Join, Leave, Move } kind = Kind::Query;
  uint64_t num = 0;                          // Query
  std::map<Gid, std::vector<Addr>> groups;   // Join
  std::vector<Gid> gids;                     // Leave
  uint64_t shard = 0;                        // Move
  Gid gid = 0;                               // Move
  CtrlOp() = default;  // non-aggregate (gcc-12, see kvraft/rsm.h)
  explicit CtrlOp(Kind k) : kind(k) {}

  static CtrlOp query(uint64_t num) {
    CtrlOp op(Kind::Query);
    op.num = num;
    return op;
  }
  static CtrlOp join(std::map<Gid, std::vector<Addr>> groups) {
    CtrlOp op(Kind::Join);
    op.groups = std::move(groups);
    return op;
  }
  static CtrlOp leave(std::vector<Gid> gids) {
    CtrlOp op(Kind::Leave);
    op.gids = std::move(gids);
    return op;
  }
  static CtrlOp move_(uint64_t shard, Gid gid) {
    CtrlOp op(Kind::Move);
    op.shard = shard;
    op.gid = gid;
    return op;
  }
};

// The replicated state: full config history (query_at must answer
// historical configs across restarts, tests.rs:64-75). configs_[i].num == i.
struct ShardInfo {
  using Command = CtrlOp;
  using Output = std::optional<Config>;

  std::vector<Config> configs{Config{}};  // config 0: all shards -> gid 0

  Output apply(const CtrlOp& op) {
    switch (op.kind) {
      case CtrlOp::Kind::Query: {
        uint64_t n = op.num;
        if (n >= configs.size()) n = configs.size() - 1;
        return configs[n];
      }
      case CtrlOp::Kind::Join: {
        MT_LOG("ctrler", "join -> config %llu",
               (unsigned long long)(configs.back().num + 1));
        Config c = configs.back();
        c.num++;
        for (auto& [gid, srvs] : op.groups) c.groups[gid] = srvs;
        rebalance(c);
        configs.push_back(std::move(c));
        return std::nullopt;
      }
      case CtrlOp::Kind::Leave: {
        Config c = configs.back();
        c.num++;
        for (Gid g : op.gids) c.groups.erase(g);
        rebalance(c);
        configs.push_back(std::move(c));
        return std::nullopt;
      }
      case CtrlOp::Kind::Move: {
        // Rejections (out-of-range shard; a gid that never joined, which
        // downstream shardkv would try to pull from with no servers and
        // wedge) return the CURRENT config so callers can distinguish
        // rejected (Some) from applied (None) — round-2 advisory: a silent
        // drop was indistinguishable from success at the clerk API.
        if (op.shard >= N_SHARDS ||
            (op.gid != 0 && !configs.back().groups.count(op.gid)))
          return configs.back();
        Config c = configs.back();
        c.num++;
        c.shards[op.shard] = op.gid;
        configs.push_back(std::move(c));
        return std::nullopt;
      }
    }
    return std::nullopt;
  }

  // Deterministic minimal-move rebalance: compute per-group targets
  // (base = N/G, the `extra` groups currently holding the most — ties by
  // ascending gid — keep one more), release only surplus shards, hand them
  // to groups below target. Shards never move between two groups that both
  // keep their target, which is exactly the minimality the tests assert
  // (tests.rs:122-163, 239-278).
  static void rebalance(Config& c) {
    if (c.groups.empty()) {
      c.shards.fill(0);
      return;
    }
    size_t ngroups = c.groups.size();
    size_t base = N_SHARDS / ngroups;
    size_t extra = N_SHARDS % ngroups;
    int bug = ctrl_bug_mode();
    // rotate_tiebreak: gid tie-breaks compare (gid + rot) mod (max gid + 1)
    // instead of gid — a per-replica permutation of the iteration order, the
    // batched backend's bug_rotate_tiebreak (ctrler.py). rot=0 = canonical.
    uint64_t rot = bug == 1 ? ctrl_rot() : 0;
    // max gid + 1 wraps to 0 when a caller joins gid UINT64_MAX; a zero
    // modulus would be UB in rkey. rot==0 needs no permutation at all, and
    // under bug mode 1 the saturated modulus still permutes every real gid.
    uint64_t mod = c.groups.rbegin()->first + 1;
    auto rkey = [&](Gid g) {
      return (rot == 0 || mod == 0) ? g : (g + rot) % mod;
    };

    std::map<Gid, size_t> count;
    for (auto& [gid, _] : c.groups) count[gid] = 0;
    for (size_t s = 0; s < N_SHARDS; s++) {
      auto it = count.find(c.shards[s]);
      if (it == count.end())
        c.shards[s] = 0;  // owner gone (or never assigned): orphan
      else
        it->second++;
    }

    if (bug == 2) {
      // greedy_rebalance: every orphan to the single least-loaded group at
      // entry, no balancing pass (ctrler.py bug_greedy_rebalance)
      Gid dst = 0;
      size_t best = N_SHARDS + 1;
      for (auto& [gid, n] : count)
        if (n < best) best = n, dst = gid;
      for (size_t s = 0; s < N_SHARDS; s++)
        if (c.shards[s] == 0) c.shards[s] = dst;
      return;
    }
    if (bug == 3) {
      // full_reshuffle: shard s -> s-th member round-robin, retention-blind
      // (ctrler.py bug_full_reshuffle)
      std::vector<Gid> memb;
      for (auto& [gid, _] : c.groups) memb.push_back(gid);
      for (size_t s = 0; s < N_SHARDS; s++)
        c.shards[s] = memb[s % memb.size()];
      return;
    }

    // pick which groups get base+1: the currently-largest (fewest moves),
    // ties broken by ascending gid for cross-replica determinism. NOT
    // rotated under the planted bug: the TPU bug_rotate_tiebreak rotates
    // only the deficit-fill order (ctrler.py _rebalance step 4; its
    // _retained_targets ranking stays canonical), which keeps balance and
    // the move COUNT replica-invariant while the assignment diverges —
    // the C++ mirror must do the same.
    std::vector<std::pair<Gid, size_t>> order(count.begin(), count.end());
    std::stable_sort(order.begin(), order.end(),
                     [](const auto& a, const auto& b) {
                       return a.second != b.second ? a.second > b.second
                                                   : a.first < b.first;
                     });
    std::map<Gid, size_t> target;
    for (size_t i = 0; i < order.size(); i++)
      target[order[i].first] = base + (i < extra ? 1 : 0);

    // release surplus (highest shard index first — any fixed rule works)
    std::vector<size_t> orphans;
    for (size_t s = 0; s < N_SHARDS; s++)
      if (c.shards[s] == 0) orphans.push_back(s);
    for (size_t s = N_SHARDS; s-- > 0;) {
      Gid g = c.shards[s];
      if (g != 0 && count[g] > target[g]) {
        count[g]--;
        c.shards[s] = 0;
        orphans.push_back(s);
      }
    }
    std::sort(orphans.begin(), orphans.end());

    // fill deficits in ascending gid order (rotated under the planted bug)
    std::vector<Gid> fill_order;
    for (auto& [gid, _] : target) fill_order.push_back(gid);
    std::sort(fill_order.begin(), fill_order.end(),
              [&](Gid a, Gid b) { return rkey(a) < rkey(b); });
    size_t oi = 0;
    for (Gid gid : fill_order) {
      while (count[gid] < target[gid]) {
        c.shards[orphans[oi++]] = gid;
        count[gid]++;
      }
    }
  }

  static void enc_cmd(Enc& e, const CtrlOp& op) {
    e.u64(uint64_t(op.kind));
    e.u64(op.num);
    e.u64(op.groups.size());
    for (auto& [gid, srvs] : op.groups) {
      e.u64(gid);
      e.u64(srvs.size());
      for (auto a : srvs) e.u64(a);
    }
    e.u64(op.gids.size());
    for (auto g : op.gids) e.u64(g);
    e.u64(op.shard);
    e.u64(op.gid);
  }
  static CtrlOp dec_cmd(Dec& d) {
    CtrlOp op;
    op.kind = CtrlOp::Kind(d.u64());
    op.num = d.u64();
    uint64_t ng = d.u64();
    for (uint64_t i = 0; i < ng; i++) {
      Gid gid = d.u64();
      auto& srvs = op.groups[gid];
      uint64_t ns = d.u64();
      for (uint64_t j = 0; j < ns; j++) srvs.push_back(Addr(d.u64()));
    }
    uint64_t ngids = d.u64();
    for (uint64_t i = 0; i < ngids; i++) op.gids.push_back(d.u64());
    op.shard = d.u64();
    op.gid = d.u64();
    return op;
  }

  static void enc_out(Enc& e, const Output& o) {
    e.u64(o.has_value() ? 1 : 0);
    if (o) Config::enc(e, *o);
  }
  static Output dec_out(Dec& d) {
    if (d.u64() == 0) return std::nullopt;
    return Config::dec(d);
  }

  void save(Enc& e) const {
    e.u64(configs.size());
    for (auto& c : configs) Config::enc(e, c);
  }
  void load(Dec& d) {
    configs.clear();
    uint64_t n = d.u64();
    for (uint64_t i = 0; i < n; i++) configs.push_back(Config::dec(d));
  }
};

// Raft-free config fan-out read (no reference analogue; the reference's
// server.rs:12-14 poll loop rides the linearizable clerk). A shardkv group
// learns "config num N exists" by asking ANY ctrler replica for exactly num
// N out of its applied state. No raft commit, no clerk seq, no dup-table
// entry: the op is idempotent and a stale replica simply answers ok=false,
// so staleness delays learning but can never corrupt it (the group adopts
// configs strictly in num order regardless of who answered). This is what
// keeps the 4B config pipeline's latency ~1 RTT instead of riding ctrler
// leader churn — seed 7036 (PERF.md) showed the clerk path taking >2 virtual
// seconds per query under loss, starving a group of a config until the test
// killed it mid-migration.
struct ConfigRead {
  uint64_t num = 0;
  struct Reply {
    bool ok = false;
    raftcore::Bytes data;  // encoded Config, valid iff ok
    Reply() = default;     // non-aggregate (gcc-12 coroutine relocation)
  };
  ConfigRead() = default;
  explicit ConfigRead(uint64_t n) : num(n) {}
};

class ShardCtrler : public RsmServer<ShardInfo> {
 public:
  static Task<std::shared_ptr<ShardCtrler>> boot(
      Sim* sim, std::vector<Addr> servers, size_t me,
      std::optional<size_t> max_raft_state) {
    auto self = co_await RsmServer<ShardInfo>::boot_as<ShardCtrler>(
        sim, std::move(servers), me, max_raft_state);
    sim->add_rpc_handler<ConfigRead>([self](ConfigRead a) {
      return handle_read(self, a);
    });
    co_return self;
  }

 private:
  friend class RsmServer<ShardInfo>;  // boot_as constructs us
  ShardCtrler(Sim* sim, std::vector<Addr> servers, size_t me,
              std::optional<size_t> mrs)
      : RsmServer<ShardInfo>(sim, std::move(servers), me, mrs) {}

  static Task<ConfigRead::Reply> handle_read(std::shared_ptr<ShardCtrler> self,
                                             ConfigRead a) {
    ConfigRead::Reply rep;
    const auto& configs = self->state().configs;
    if (a.num < configs.size()) {
      Enc e;
      Config::enc(e, configs[a.num]);
      rep.ok = true;
      rep.data = std::move(e.out);
    }
    co_return rep;
  }
};

// client.rs:9-35 — the clerk reuses the generic retrying core
class CtrlerClerk {
 public:
  CtrlerClerk(Sim* sim, std::vector<Addr> servers, uint64_t id)
      : core_(sim, std::move(servers), id) {}

  Task<Config> query() { return unwrap(core_.call(CtrlOp::query(LATEST))); }
  Task<Config> query_at(uint64_t num) {
    return unwrap(core_.call(CtrlOp::query(num)));
  }
  Task<void> join(std::map<Gid, std::vector<Addr>> groups) {
    return drop(core_.call(CtrlOp::join(std::move(groups))));
  }
  Task<void> leave(std::vector<Gid> gids) {
    return drop(core_.call(CtrlOp::leave(std::move(gids))));
  }
  // DEVIATION from the reference (which applies Move verbatim,
  // shard_ctrler/server.rs): a Move targeting a gid that never joined is
  // REJECTED — it commits through raft but produces no new config, because
  // downstream shardkv would try to pull the shard from an owner with no
  // servers and wedge. Returns true if the move was applied, false if
  // rejected (the apply path answers a rejection with the unchanged current
  // config instead of None).
  Task<bool> move_(uint64_t shard, Gid gid) {
    return applied(core_.call(CtrlOp::move_(shard, gid)));
  }
  uint64_t id() const { return core_.id(); }
  const std::vector<Addr>& servers() const { return core_.servers(); }

 private:
  static Task<Config> unwrap(Task<std::optional<Config>> t) {
    auto c = co_await std::move(t);
    co_return *c;
  }
  static Task<void> drop(Task<std::optional<Config>> t) {
    co_await std::move(t);
  }
  static Task<bool> applied(Task<std::optional<Config>> t) {
    auto c = co_await std::move(t);
    co_return !c.has_value();
  }
  ClerkCore<ShardInfo> core_;
};

}  // namespace shard_ctrler
