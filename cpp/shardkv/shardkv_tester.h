// ShardKV tester — the C++ analogue of the reference's 4B harness
// (SURVEY.md §2 C16, /root/reference/src/shardkv/tester.rs):
//   * topology: 3 ctrler servers at 0.0.1.i + 3 groups (gid 100/101/102) × n
//     servers at 0.1.g.j (tester.rs:47-70)
//   * group-level start/shutdown (tester.rs:136-172)
//   * ctrl-plane join/leave via a ctrler clerk (tester.rs:174-199)
//   * query_shards_of(group) (tester.rs:202-206)
//   * storage checkers: check_logs (state ≤ 8×limit; snapshot empty when no
//     limit, tester.rs:91-111) and total_size for the deletion challenge
//     (tester.rs:113-123)
//   * deterministic rand_string values from the sim RNG (tester.rs:264-270)
#pragma once

#include <cstdio>
#include <memory>
#include <set>

#include "../tests/framework.h"
#include "shardkv.h"

namespace shardkv {

using simcore::make_addr;
using simcore::SEC;
using simcore::TaskRef;

class ShardKvTester {
 public:
  static constexpr int N_GROUPS = 3;

  ShardKvTester(Sim* sim, int n, bool unreliable,
                std::optional<size_t> max_raft_state)
      : sim_(sim), n_(n), max_raft_state_(max_raft_state) {
    if (unreliable) {  // tester.rs:40-45
      auto& cfg = sim_->net_config();
      cfg.packet_loss_rate = 0.1;
      cfg.send_latency_min = 1 * simcore::MSEC;
      cfg.send_latency_max = 27 * simcore::MSEC;
    }
    for (int i = 0; i < 3; i++) ctrler_addrs_.push_back(make_addr(0, 0, 1, i));
    for (int g = 0; g < N_GROUPS; g++) {
      Group grp;
      grp.gid = 100 + g;  // tester.rs:64
      for (int j = 0; j < n; j++)
        grp.addrs.push_back(make_addr(0, 1, g, j));  // tester.rs:66
      grp.servers.resize(n);
      groups_.push_back(std::move(grp));
    }
    start_time_ = sim->now();
  }

  Task<void> init() {
    for (size_t i = 0; i < ctrler_addrs_.size(); i++) {
      ctrlers_.push_back(co_await sim_->spawn(
          ctrler_addrs_[i],
          shard_ctrler::ShardCtrler::boot(sim_, ctrler_addrs_, i,
                                          max_raft_state_)));
    }
    ctrler_ck_ = std::make_shared<CtrlerClerk>(sim_, ctrler_addrs_, next_id_++);
    for (int g = 0; g < N_GROUPS; g++)
      for (int i = 0; i < n_; i++) co_await sim_->spawn(start_server(g, i));
  }

  Sim* sim() { return sim_; }
  int n() const { return n_; }
  Gid gid_of(int group) const { return groups_[group].gid; }

  // ---- server lifecycle (tester.rs:136-172)
  Task<void> start_server(int group, int i) {
    auto& g = groups_[group];
    auto ctrl_ck =
        std::make_shared<CtrlerClerk>(sim_, ctrler_addrs_, next_id_++);
    g.servers[i] = co_await sim_->spawn(
        g.addrs[i], ShardKvServer::boot(sim_, ctrl_ck, g.addrs, g.gid, i,
                                        max_raft_state_));
  }
  void shutdown_server(int group, int i) {
    sim_->kill(groups_[group].addrs[i]);
    groups_[group].servers[i] = nullptr;
  }
  Task<void> start_group(int group) {
    for (int i = 0; i < n_; i++) co_await sim_->spawn(start_server(group, i));
  }
  void shutdown_group(int group) {
    for (int i = 0; i < n_; i++) shutdown_server(group, i);
  }

  // ---- ctrl plane (tester.rs:174-199)
  Task<void> join(int group) { return joins({group}); }
  Task<void> joins(std::vector<int> groups) {
    std::map<Gid, std::vector<Addr>> m;
    for (int g : groups) m[groups_[g].gid] = groups_[g].addrs;
    co_await sim_->spawn(ctrler_ck_->join(std::move(m)));
  }
  Task<void> leave(int group) { return leaves({group}); }
  Task<void> leaves(std::vector<int> groups) {
    std::vector<Gid> gids;
    for (int g : groups) gids.push_back(groups_[g].gid);
    co_await sim_->spawn(ctrler_ck_->leave(std::move(gids)));
  }

  // tester.rs:202-206
  Task<std::set<size_t>> query_shards_of(int group) {
    Config c = co_await sim_->spawn(ctrler_ck_->query());
    std::set<size_t> owned;
    for (size_t s = 0; s < N_SHARDS; s++)
      if (c.shards[s] == groups_[group].gid) owned.insert(s);
    co_return owned;
  }

  // ---- storage checkers (tester.rs:91-123)
  void check_logs() const {
    for (auto& g : groups_) {
      for (Addr a : g.addrs) {
        size_t state_size = sim_->fs_size(a, "state");
        size_t snap_size = sim_->fs_size(a, "snapshot");
        if (max_raft_state_) {
          if (state_size > 8 * *max_raft_state_) {
            std::fprintf(stderr, "raft state size %zu exceeds limit %zu\n",
                         state_size, 8 * *max_raft_state_);
            std::abort();
          }
        } else if (snap_size != 0) {
          std::fprintf(stderr,
                       "max_raft_state is None, but snapshot is non-empty\n");
          std::abort();
        }
      }
    }
  }
  size_t total_size() const {
    size_t size = 0;
    for (auto& g : groups_)
      for (Addr a : g.addrs)
        size += sim_->fs_size(a, "state") + sim_->fs_size(a, "snapshot");
    return size;
  }

  // ---- clerks (tester.rs:131-133, 234-261)
  class Clerk {
   public:
    Clerk(Sim* sim, Addr addr, std::shared_ptr<ShardClerk> ck)
        : sim_(sim), addr_(addr), ck_(std::move(ck)) {}

    Task<void> put(std::string k, std::string v) {
      co_await sim_->spawn(addr_, ck_->put(std::move(k), std::move(v)));
    }
    Task<void> append(std::string k, std::string v) {
      co_await sim_->spawn(addr_, ck_->append(std::move(k), std::move(v)));
    }
    Task<std::string> get(std::string k) {
      co_return co_await sim_->spawn(addr_, ck_->get(std::move(k)));
    }
    Task<void> check(std::string k, std::string expected) {  // tester.rs:241-244
      auto v = co_await get(k);
      if (v != expected) {
        std::fprintf(stderr, "check failed: key=%s got %.60s want %.60s\n",
                     k.c_str(), v.c_str(), expected.c_str());
        std::abort();
      }
    }

    using Kvs = std::vector<std::pair<std::string, std::string>>;
    Task<void> put_kvs(const Kvs& kvs) {  // tester.rs:235-239
      for (auto& [k, v] : kvs) co_await put(k, v);
    }
    Task<void> check_kvs(const Kvs& kvs) {  // tester.rs:246-251
      for (auto& [k, v] : kvs) co_await check(k, v);
    }
    // tester.rs:253-261: verify, then append a fresh random suffix
    Task<void> check_append_kvs(Kvs& kvs, size_t len) {
      for (auto& [k, v] : kvs) {
        co_await check(k, v);
        auto s = rand_string(sim_, len);
        v += s;
        co_await append(k, s);
      }
    }

   private:
    Sim* sim_;
    Addr addr_;
    std::shared_ptr<ShardClerk> ck_;
  };

  Clerk make_client() {  // tester.rs:131-133
    uint64_t kv_id = next_id_++;
    uint64_t ctrl_id = next_id_++;
    Addr addr = make_addr(0, 0, 3, next_clerk_addr_++);
    return Clerk(sim_, addr,
                 std::make_shared<ShardClerk>(sim_, ctrler_addrs_, kv_id,
                                              ctrl_id));
  }

  // tester.rs:264-270 — deterministic alphanumeric values from the sim RNG
  static std::string rand_string(Sim* sim, size_t len) {
    static const char cs[] =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789";
    std::string s;
    for (size_t i = 0; i < len; i++) s += cs[sim->rand_range(0, 62)];
    return s;
  }

  void end() const {  // tester.rs:212-224
    std::printf("  ... elapsed %.2fs(virt) peers %d rpcs %llu\n",
                (sim_->now() - start_time_) / 1e9, n_,
                (unsigned long long)(sim_->msg_count() / 2));
  }

 private:
  struct Group {
    Gid gid = 0;
    std::vector<Addr> addrs;
    std::vector<std::shared_ptr<ShardKvServer>> servers;
    Group() = default;
  };

  Sim* sim_;
  int n_;
  std::optional<size_t> max_raft_state_;
  uint64_t start_time_;
  std::vector<Addr> ctrler_addrs_;
  std::vector<std::shared_ptr<shard_ctrler::ShardCtrler>> ctrlers_;
  std::shared_ptr<CtrlerClerk> ctrler_ck_;
  std::vector<Group> groups_;
  uint64_t next_id_ = 0;
  unsigned next_clerk_addr_ = 1;
};

}  // namespace shardkv
