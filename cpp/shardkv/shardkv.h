// shardkv — the Lab 4B multi-group sharded KV service (SURVEY.md §2 C9,
// /root/reference/src/shardkv/):
//   key2shard = first byte % N_SHARDS        (mod.rs:12-15, "do not change")
//   Op::{Get, Put, Append}; Reply::{Get{value}, Ok, WrongGroup}  (msg.rs:3-15)
//   ShardKvServer::new(ctrl_ck, servers, gid, me, max_raft_state)
//                                            (server.rs:12-18, todo!())
//   Clerk routes by config, retries on WrongGroup  (client.rs:16-25, todo!())
//
// The reference leaves the whole server/client as todo!() stubs; this is a
// from-scratch design for the full lab including both challenges
// (tests.rs:438-605):
//
//  * One Raft group per gid. The replicated state machine consumes a tagged
//    command stream: client ops, config installs, shard installs, shard
//    erases, and ack-dones. Everything that must survive crashes — current
//    config, pending pulls, frozen outgoing shards, unacked installs — is
//    replicated state, snapshotted together with the data.
//  * Data and dup-tables are PER SHARD so they migrate with the shard: a
//    clerk retry that lands on the shard's new owner still deduplicates
//    (the record traveled inside InstallShard).
//  * Config changes advance one step at a time (num+1) and only when the
//    current config's pulls are complete; that gates chained migrations
//    (the at-config-N owner has the data before it freezes the shard for
//    the config-N+1 owner).
//  * Serving is per shard: owned && not mid-pull. A shard received early in
//    a partially-completed migration serves immediately (challenge 2,
//    tests.rs:499-605); unaffected shards never stop serving.
//  * Losing a shard freezes it into `outgoing[{config,shard}]`; the new
//    owner pulls it, commits InstallShard, then acks until the source
//    commits EraseShard (challenge 1 storage bound, tests.rs:477-488). Both
//    sides are idempotent, so every RPC may be retried blindly.
#pragma once

#include <array>
#include <cstdlib>
#include <cstring>
#include <map>
#include <optional>
#include <string>
#include <vector>

#include "../shard_ctrler/ctrler.h"

namespace shardkv {

using raftcore::ApplyMsg;
using raftcore::Bytes;
using raftcore::Dec;
using raftcore::Enc;
using raftcore::Raft;
using shard_ctrler::Config;
using shard_ctrler::CtrlerClerk;
using shard_ctrler::Gid;
using shard_ctrler::N_SHARDS;
using simcore::Addr;
using simcore::Channel;
using simcore::MSEC;
using simcore::Sim;
using simcore::Task;

// mod.rs:12-15 — "please do not change it"
inline size_t key2shard(const std::string& key) {
  return size_t(key.empty() ? 0 : uint8_t(key[0])) % N_SHARDS;
}

// Deliberate-bug injection for the TPU<->C++ differential bridge
// (madraft_tpu/bridge.py): the TPU fuzzer finds a violation under one of its
// service bug modes; the C++ replay runs the SAME protocol bug so its
// client-side checkers must observe the same violation class. Env-gated so
// the production build path is untouched.
//   MADTPU_SHARDKV_BUG=drop_dup_table  — InstallShard discards the migrated
//                                        dup table (exactly-once breaks
//                                        across migration)
//   MADTPU_SHARDKV_BUG=serve_frozen    — a leader skips the ownership check
//                                        for reads and serves Gets from
//                                        whatever local copy exists
// Name -> mode mapping, shared with the schedule parser's whitelist
// (cpp/tools/shardkv_replay_core.h): a name this function does not know is
// NOT a valid schedule bug, so the two can never drift apart.
inline int bug_mode_of(const char* name) {
  if (!name) return 0;
  if (!std::strcmp(name, "drop_dup_table")) return 1;
  if (!std::strcmp(name, "serve_frozen")) return 2;
  return 0;
}

inline bool is_known_service_bug(const std::string& name) {
  return name == "none" || bug_mode_of(name.c_str()) != 0;
}

inline int bug_mode() {
  // read per call, NOT cached statically: the in-process C API
  // (cpp/tools/capi.cpp) runs replays with different bug modes in one
  // process; this is a cold path (client ops + installs)
  return bug_mode_of(std::getenv("MADTPU_SHARDKV_BUG"));
}

// msg.rs:3-8
struct Op {
  enum class Kind : uint8_t { Get, Put, Append } kind = Kind::Get;
  std::string key;
  std::string value;
  Op() = default;  // non-aggregate (gcc-12 coroutine relocation, see rsm.h)
  Op(Kind k, std::string key_, std::string value_)
      : kind(k), key(std::move(key_)), value(std::move(value_)) {}
};

// msg.rs:10-15 — Reply::{Get{value}, Ok, WrongGroup}; NotLeader/Failed drive
// clerk retry like the kvraft codes (they never commit through raft).
enum class Code : uint8_t { Ok, WrongGroup, NotLeader, Failed };

struct KvReply {
  Code code = Code::Failed;
  int hint = -1;
  std::string value;  // Get result
  KvReply() = default;
  KvReply(Code c, int h = -1, std::string v = {})
      : code(c), hint(h), value(std::move(v)) {}
};

struct KvRequest {
  uint64_t client = 0;
  uint64_t seq = 0;
  Op op;
  using Reply = KvReply;
  KvRequest() = default;
  KvRequest(uint64_t c, uint64_t s, Op o)
      : client(c), seq(s), op(std::move(o)) {}
};

// One shard's migratable payload: data + its dup table (so exactly-once
// survives the move).
struct ShardData {
  std::map<std::string, std::string> kv;
  struct DupRec {
    uint64_t seq = 0;
    std::string value;  // cached Get output
    bool has_value = false;
  };
  std::map<uint64_t, DupRec> dup;
  ShardData() = default;

  void enc(Enc& e) const {
    e.u64(kv.size());
    for (auto& [k, v] : kv) {
      e.str(k);
      e.str(v);
    }
    e.u64(dup.size());
    for (auto& [c, r] : dup) {
      e.u64(c);
      e.u64(r.seq);
      e.u64(r.has_value ? 1 : 0);
      e.str(r.value);
    }
  }
  static ShardData dec(Dec& d) {
    ShardData s;
    uint64_t n = d.u64();
    for (uint64_t i = 0; i < n; i++) {
      auto k = d.str();
      s.kv[k] = d.str();
    }
    uint64_t m = d.u64();
    for (uint64_t i = 0; i < m; i++) {
      auto& r = s.dup[d.u64()];
      r.seq = d.u64();
      r.has_value = d.u64() != 0;
      r.value = d.str();
    }
    return s;
  }
};

// Inter-group migration RPCs (both leader-served, both idempotent).
struct PullShardArgs {
  uint64_t config_num = 0;
  uint64_t shard = 0;
  struct Reply {
    // Ok: payload attached. NotReady: source hasn't reached config_num yet.
    // Gone: already erased (duplicate pull after ack — ignore).
    // NotLeader: try another server.
    enum class Code : uint8_t { Ok, NotReady, Gone, NotLeader } code =
        Code::NotLeader;
    Bytes data;  // encoded ShardData
    Reply() = default;
  };
  PullShardArgs() = default;
  PullShardArgs(uint64_t c, uint64_t s) : config_num(c), shard(s) {}
};

struct AckPullArgs {
  uint64_t config_num = 0;
  uint64_t shard = 0;
  struct Reply {
    bool ok = false;  // erased (or was already gone)
    Reply() = default;
  };
  AckPullArgs() = default;
  AckPullArgs(uint64_t c, uint64_t s) : config_num(c), shard(s) {}
};

// ------------------------------------------------------------------- server
class ShardKvServer : public std::enable_shared_from_this<ShardKvServer> {
  // Raft log command tags.
  enum class Cmd : uint8_t { Client, Config, Install, Erase, AckDone };

  struct PullInfo {
    uint64_t config_num = 0;
    Gid src_gid = 0;
    std::vector<Addr> src_servers;
  };

 public:
  static Task<std::shared_ptr<ShardKvServer>> boot(
      Sim* sim, std::shared_ptr<CtrlerClerk> ctrl_ck, std::vector<Addr> servers,
      Gid gid, size_t me, std::optional<size_t> max_raft_state) {
    auto self = std::shared_ptr<ShardKvServer>(
        new ShardKvServer(sim, std::move(ctrl_ck), servers, gid, me,
                          max_raft_state));
    self->raft_ =
        co_await sim->spawn(Raft::boot(sim, servers, me, self->apply_ch_));
    sim->add_rpc_handler<KvRequest>(
        [self](KvRequest req) { return handle_client(self, std::move(req)); });
    sim->add_rpc_handler<PullShardArgs>([self](PullShardArgs a) {
      return handle_pull(self, a);
    });
    sim->add_rpc_handler<AckPullArgs>([self](AckPullArgs a) {
      return handle_ack(self, a);
    });
    sim->spawn(applier(self));
    sim->spawn(config_poller(self));
    sim->spawn(migrator(self));
    co_return self;
  }

  uint64_t term() const { return raft_->term(); }
  bool is_leader() const { return raft_->is_leader(); }

 private:
  ShardKvServer(Sim* sim, std::shared_ptr<CtrlerClerk> ctrl_ck,
                std::vector<Addr> servers, Gid gid, size_t me,
                std::optional<size_t> mrs)
      : sim_(sim), ctrl_ck_(std::move(ctrl_ck)), addr_(servers[me]), gid_(gid),
        max_raft_state_(mrs) {}

  bool serving(size_t shard) const {
    return config_.shards[shard] == gid_ && !pull_pending_.count(shard);
  }

  // ---- client path (server.rs:52-56 analogue, WrongGroup decided at apply)
  static Task<KvReply> handle_client(std::shared_ptr<ShardKvServer> self,
                                     KvRequest req) {
    // Fast reject so clerks don't burn 500ms on a non-serving group — but
    // only on the leader: a follower's config may lag, and a spurious
    // WrongGroup from a stale follower would send the clerk back to the
    // ctrler in a loop. Followers answer NotLeader (via start()) instead.
    size_t shard = key2shard(req.op.key);
    if (bug_mode() == 2 && self->raft_->is_leader() &&
        !self->serving(shard) && req.op.kind == Op::Kind::Get) {
      // BUG (bridge validation): serve the read from whatever local copy
      // exists — the latest frozen outgoing copy, or nothing after GC
      const ShardData* src = &self->shards_[shard];
      for (auto it = self->outgoing_.rbegin(); it != self->outgoing_.rend();
           ++it) {
        if (it->first.second == shard) {
          src = &it->second;
          break;
        }
      }
      auto kv = src->kv.find(req.op.key);
      co_return KvReply{Code::Ok, -1,
                        kv == src->kv.end() ? std::string() : kv->second};
    }
    if (self->raft_->is_leader() && !self->serving(shard))
      co_return KvReply{Code::WrongGroup};
    Enc e;
    e.u64(uint64_t(Cmd::Client));
    e.u64(req.client);
    e.u64(req.seq);
    e.u64(uint64_t(req.op.kind));
    e.str(req.op.key);
    e.str(req.op.value);
    auto r = self->raft_->start(std::move(e.out));
    if (!r.ok) co_return KvReply{Code::NotLeader, r.hint};
    if (!co_await kvraft::wait_applied(self->sim_, *self->raft_,
                                       self->applied_, r.index, r.term))
      co_return KvReply{Code::Failed};
    auto it = self->results_.find(r.index);
    if (it != self->results_.end() && it->second.client == req.client &&
        it->second.seq == req.seq) {
      co_return it->second.reply;
    }
    co_return KvReply{Code::Failed};  // different entry won our index
  }

  // ---- migration read side: serve a frozen shard to its new owner
  static Task<PullShardArgs::Reply> handle_pull(
      std::shared_ptr<ShardKvServer> self, PullShardArgs a) {
    PullShardArgs::Reply rep;
    if (!self->raft_->is_leader()) {
      rep.code = PullShardArgs::Reply::Code::NotLeader;
      co_return rep;
    }
    if (self->config_.num < a.config_num) {
      rep.code = PullShardArgs::Reply::Code::NotReady;
      co_return rep;
    }
    auto it = self->outgoing_.find({a.config_num, a.shard});
    if (it == self->outgoing_.end()) {
      rep.code = PullShardArgs::Reply::Code::Gone;
      co_return rep;
    }
    Enc e;
    it->second.enc(e);
    rep.code = PullShardArgs::Reply::Code::Ok;
    rep.data = std::move(e.out);
    co_return rep;
  }

  // ---- migration GC side: new owner confirms install; we erase (challenge 1)
  static Task<AckPullArgs::Reply> handle_ack(std::shared_ptr<ShardKvServer> self,
                                             AckPullArgs a) {
    AckPullArgs::Reply rep;
    if (!self->raft_->is_leader()) co_return rep;  // ok=false → retry
    // Same staleness guard as handle_pull: a freshly elected leader that has
    // not yet applied the config-N freeze would otherwise report "already
    // erased" for a shard it still holds, and the puller would stop acking —
    // leaking the frozen shard forever (challenge-1 storage bound).
    if (self->config_.num < a.config_num) co_return rep;
    if (!self->outgoing_.count({a.config_num, a.shard})) {
      rep.ok = true;  // already erased — idempotent success
      co_return rep;
    }
    Enc e;
    e.u64(uint64_t(Cmd::Erase));
    e.u64(a.config_num);
    e.u64(a.shard);
    auto r = self->raft_->start(std::move(e.out));
    if (!r.ok) co_return rep;
    if (!co_await kvraft::wait_applied(self->sim_, *self->raft_,
                                       self->applied_, r.index, r.term))
      co_return rep;
    rep.ok = !self->outgoing_.count({a.config_num, a.shard});
    co_return rep;
  }

  // ---- config poller: fetch config num+1 when the current migration is done
  // (server.rs:12-14 — the reference hands the server a ctrler clerk for this
  // loop). NOT via the linearizable clerk: each clerk query commits a raft
  // entry in the ctrler cluster and retries with 500 ms timeouts, so under
  // loss + ctrler leader churn a single query can block for virtual SECONDS
  // (seed 7036, PERF.md: group 100 starved of config 2 until the test killed
  // it mid-migration, wedging its successor's pulls forever). The poller only
  // needs "does config num+1 exist, and what is it" — an idempotent exact-num
  // read — so it asks each ctrler replica directly via the raft-free
  // ConfigRead fan-out; any replica that has applied the config answers.
  static Task<void> config_poller(std::shared_ptr<ShardKvServer> self) {
    for (;;) {
      co_await self->sim_->sleep(50 * MSEC);
      if (!self->raft_->is_leader()) continue;
      if (!self->pull_pending_.empty()) continue;  // finish migration first
      uint64_t want = self->config_.num + 1;
      std::optional<Config> found;
      // rotate the probe start so a dead/partitioned replica taxes only
      // every n-th round with its 100 ms timeout, not all of them
      const auto& ctrlers = self->ctrl_ck_->servers();
      size_t start = self->poll_round_++ % ctrlers.size();
      for (size_t k = 0; k < ctrlers.size(); k++) {
        Addr a = ctrlers[(start + k) % ctrlers.size()];
        auto rep = co_await self->sim_->call_timeout(
            a, shard_ctrler::ConfigRead{want}, 100 * MSEC);
        if (rep && rep->ok) {
          Dec d(rep->data);
          found = Config::dec(d);
          break;
        }
      }
      if (!found || found->num != want) continue;  // no newer config yet
      if (self->config_.num + 1 != want || !self->pull_pending_.empty())
        continue;  // state moved while we awaited the reads
      Enc e;
      e.u64(uint64_t(Cmd::Config));
      Config::enc(e, *found);
      self->raft_->start(std::move(e.out));
    }
  }

  // ---- migration write side: pull pending shards, then ack installs.
  // One task per shard per round, so a dead source (challenge 2: pulls that
  // can never finish) only costs its own task's timeouts, not a serial stall
  // of every other shard's migration and GC.
  static Task<void> pull_one(std::shared_ptr<ShardKvServer> self,
                             uint64_t shard, PullInfo info) {
    // still pending for this config? (a snapshot/commit may have landed)
    auto cur = self->pull_pending_.find(shard);
    if (cur == self->pull_pending_.end() ||
        cur->second.config_num != info.config_num)
      co_return;
    for (size_t i = 0; i < info.src_servers.size(); i++) {
      auto rep = co_await self->sim_->call_timeout(
          info.src_servers[i], PullShardArgs{info.config_num, shard},
          200 * MSEC);
      if (!rep) continue;
      using C = PullShardArgs::Reply::Code;
      if (rep->code == C::Ok) {
        Enc e;
        e.u64(uint64_t(Cmd::Install));
        e.u64(info.config_num);
        e.u64(shard);
        e.bytes(rep->data);
        auto r = self->raft_->start(std::move(e.out));
        // wait for the install to land so the next migrator round doesn't
        // re-pull the whole payload and double-log the Install
        if (r.ok)
          co_await kvraft::wait_applied(self->sim_, *self->raft_,
                                        self->applied_, r.index, r.term);
        co_return;
      }
      if (rep->code == C::Gone) co_return;     // install already happened
      if (rep->code == C::NotReady) co_return;  // source lags; retry later
      // NotLeader → try next server
    }
  }

  static Task<void> ack_one(std::shared_ptr<ShardKvServer> self,
                            uint64_t cfg_num, uint64_t shard, PullInfo src) {
    std::pair<uint64_t, uint64_t> key(cfg_num, shard);
    if (!self->need_ack_.count(key)) co_return;
    for (size_t i = 0; i < src.src_servers.size(); i++) {
      auto rep = co_await self->sim_->call_timeout(
          src.src_servers[i], AckPullArgs{cfg_num, shard}, 200 * MSEC);
      if (rep && rep->ok) {
        Enc e;
        e.u64(uint64_t(Cmd::AckDone));
        e.u64(cfg_num);
        e.u64(shard);
        auto r = self->raft_->start(std::move(e.out));
        if (r.ok)  // same: one AckDone per completed ack, not one per round
          co_await kvraft::wait_applied(self->sim_, *self->raft_,
                                        self->applied_, r.index, r.term);
        co_return;
      }
    }
  }

  static Task<void> migrator(std::shared_ptr<ShardKvServer> self) {
    for (;;) {
      co_await self->sim_->sleep(50 * MSEC);
      if (!self->raft_->is_leader()) continue;
      std::vector<simcore::TaskRef<void>> round;
      for (auto& [shard, info] : self->pull_pending_)
        round.push_back(self->sim_->spawn(pull_one(self, shard, info)));
      for (auto& [key, src] : self->need_ack_)
        round.push_back(
            self->sim_->spawn(ack_one(self, key.first, key.second, src)));
      for (auto& t : round) co_await t;
    }
  }

  // ---- the replicated state machine
  static Task<void> applier(std::shared_ptr<ShardKvServer> self) {
    for (;;) {
      auto m = co_await self->apply_ch_.recv();
      if (!m) break;
      if (m->is_snapshot) {
        if (self->raft_->cond_install_snapshot(m->term, m->index, m->data)) {
          Dec d(m->data);
          self->load_snapshot(d);
          self->applied_ = m->index;
          self->results_.clear();
        }
        continue;
      }
      Dec d(m->data);
      self->apply_cmd(d, m->index);
      self->applied_ = m->index;
      // bound the volatile result window (handlers read their own index fast)
      while (!self->results_.empty() &&
             self->results_.begin()->first + 512 < m->index)
        self->results_.erase(self->results_.begin());
      self->maybe_snapshot(m->index);
    }
  }

  void apply_cmd(Dec& d, uint64_t index) {
    switch (Cmd(d.u64())) {
      case Cmd::Client: {
        uint64_t client = d.u64();
        uint64_t seq = d.u64();
        Op op;
        op.kind = Op::Kind(d.u64());
        op.key = d.str();
        op.value = d.str();
        size_t shard = key2shard(op.key);
        Result res;
        res.client = client;
        res.seq = seq;
        if (!serving(shard)) {
          res.reply = KvReply{Code::WrongGroup};
        } else {
          auto& sd = shards_[shard];
          auto& rec = sd.dup[client];
          if (seq > rec.seq) {  // first time: apply
            rec.seq = seq;
            rec.has_value = false;
            rec.value.clear();
            switch (op.kind) {
              case Op::Kind::Get: {
                auto it = sd.kv.find(op.key);
                rec.has_value = it != sd.kv.end();
                if (rec.has_value) rec.value = it->second;
                break;
              }
              case Op::Kind::Put:
                sd.kv[op.key] = std::move(op.value);
                break;
              case Op::Kind::Append:
                sd.kv[op.key] += op.value;
                break;
            }
          }
          // duplicate (seq <= rec.seq): serve the cached output
          res.reply = KvReply{Code::Ok, -1, rec.value};
        }
        results_[index] = std::move(res);
        break;
      }
      case Cmd::Config: {
        Config c = Config::dec(d);
        if (c.num != config_.num + 1) break;  // stale/duplicate proposal
        Config old = std::move(config_);
        config_ = std::move(c);
        MT_LOG("shardkv", "gid %llu adopts config %llu",
               (unsigned long long)gid_, (unsigned long long)config_.num);
        for (size_t s = 0; s < N_SHARDS; s++) {
          bool was = old.shards[s] == gid_;
          bool now = config_.shards[s] == gid_;
          auto src_it = old.groups.find(old.shards[s]);
          bool has_src = src_it != old.groups.end() && !src_it->second.empty();
          if (now && !was && old.shards[s] != 0 && has_src) {
            PullInfo pi;
            pi.config_num = config_.num;
            pi.src_gid = old.shards[s];
            pi.src_servers = src_it->second;
            pull_pending_[s] = std::move(pi);
          } else if (was && !now && config_.shards[s] != 0) {
            outgoing_[{config_.num, s}] = std::move(shards_[s]);
            shards_[s] = ShardData{};
          } else if (was && !now) {
            // handed to gid 0 = every group left: there is no future puller,
            // so freezing would leak the shard forever and a later joiner
            // would diverge from us. Retire the data — all groups then agree
            // the shard restarts empty (config-0 semantics).
            shards_[s] = ShardData{};
          }
        }
        break;
      }
      case Cmd::Install: {
        uint64_t cfg_num = d.u64();
        uint64_t shard = d.u64();
        Bytes data = d.bytes();
        auto it = pull_pending_.find(shard);
        if (it == pull_pending_.end() || it->second.config_num != cfg_num)
          break;  // duplicate install
        Dec sd(data);
        shards_[shard] = ShardData::dec(sd);
        if (bug_mode() == 1) shards_[shard].dup.clear();  // BUG: see bug_mode()
        MT_LOG("shardkv", "gid %llu installs shard %llu at config %llu",
               (unsigned long long)gid_, (unsigned long long)shard,
               (unsigned long long)cfg_num);
        PullInfo src = std::move(it->second);
        pull_pending_.erase(it);
        need_ack_[{cfg_num, shard}] = std::move(src);
        break;
      }
      case Cmd::Erase: {
        uint64_t cfg_num = d.u64();
        uint64_t shard = d.u64();
        MT_LOG("shardkv", "gid %llu erases shard %llu (config %llu)",
               (unsigned long long)gid_, (unsigned long long)shard,
               (unsigned long long)cfg_num);
        outgoing_.erase({cfg_num, shard});
        break;
      }
      case Cmd::AckDone: {
        uint64_t cfg_num = d.u64();
        uint64_t shard = d.u64();
        need_ack_.erase({cfg_num, shard});
        break;
      }
    }
  }

  void maybe_snapshot(uint64_t index) {
    kvraft::snapshot_if_oversized(sim_, addr_, max_raft_state_, *raft_, index,
                                  [this](Enc& e) { save_snapshot(e); });
  }

  void save_snapshot(Enc& e) const {
    Config::enc(e, config_);
    for (auto& sd : shards_) sd.enc(e);
    e.u64(pull_pending_.size());
    for (auto& [shard, pi] : pull_pending_) {
      e.u64(shard);
      e.u64(pi.config_num);
      e.u64(pi.src_gid);
      e.u64(pi.src_servers.size());
      for (auto a : pi.src_servers) e.u64(a);
    }
    e.u64(need_ack_.size());
    for (auto& [key, pi] : need_ack_) {
      e.u64(key.first);
      e.u64(key.second);
      e.u64(pi.src_gid);
      e.u64(pi.src_servers.size());
      for (auto a : pi.src_servers) e.u64(a);
    }
    e.u64(outgoing_.size());
    for (auto& [key, sd] : outgoing_) {
      e.u64(key.first);
      e.u64(key.second);
      sd.enc(e);
    }
  }
  void load_snapshot(Dec& d) {
    config_ = Config::dec(d);
    for (auto& sd : shards_) sd = ShardData::dec(d);
    pull_pending_.clear();
    uint64_t np = d.u64();
    for (uint64_t i = 0; i < np; i++) {
      uint64_t shard = d.u64();
      auto& pi = pull_pending_[shard];
      pi.config_num = d.u64();
      pi.src_gid = d.u64();
      uint64_t ns = d.u64();
      for (uint64_t j = 0; j < ns; j++) pi.src_servers.push_back(Addr(d.u64()));
    }
    need_ack_.clear();
    uint64_t na = d.u64();
    for (uint64_t i = 0; i < na; i++) {
      uint64_t cn = d.u64();
      uint64_t shard = d.u64();
      auto& pi = need_ack_[{cn, shard}];
      pi.config_num = cn;  // keep snapshot-restored state == log-replayed state
      pi.src_gid = d.u64();
      uint64_t ns = d.u64();
      for (uint64_t j = 0; j < ns; j++) pi.src_servers.push_back(Addr(d.u64()));
    }
    outgoing_.clear();
    uint64_t no = d.u64();
    for (uint64_t i = 0; i < no; i++) {
      uint64_t cn = d.u64();
      uint64_t shard = d.u64();
      outgoing_[{cn, shard}] = ShardData::dec(d);
    }
  }

  struct Result {
    uint64_t client = 0;
    uint64_t seq = 0;
    KvReply reply;
  };

  Sim* sim_;
  std::shared_ptr<CtrlerClerk> ctrl_ck_;
  Addr addr_;
  Gid gid_;
  std::optional<size_t> max_raft_state_;
  uint64_t poll_round_ = 0;  // rotates the ConfigRead probe start
  Channel<ApplyMsg> apply_ch_;
  std::shared_ptr<Raft> raft_;
  uint64_t applied_ = 0;

  // replicated state (snapshotted)
  Config config_;  // num 0: nothing owned
  std::array<ShardData, N_SHARDS> shards_;
  std::map<uint64_t, PullInfo> pull_pending_;  // shard -> source
  std::map<std::pair<uint64_t, uint64_t>, PullInfo> need_ack_;
  std::map<std::pair<uint64_t, uint64_t>, ShardData> outgoing_;

  // volatile
  std::map<uint64_t, Result> results_;  // raft index -> applied result
};

// ------------------------------------------------------------------- client
// client.rs:4-26 — owns a ctrler clerk, routes by cached config, re-queries
// on WrongGroup, retries forever.
// CONTRACT: one outstanding op at a time per ShardClerk (same as ClerkCore,
// rsm.h): seq advances per op, and the per-shard dup tables treat any
// lower-seq arrival as an already-answered duplicate — concurrent ops on one
// clerk could silently swallow the older one. Tests honor this (each
// concurrent task owns its own clerk).
class ShardClerk : public std::enable_shared_from_this<ShardClerk> {
 public:
  ShardClerk(Sim* sim, std::vector<Addr> ctrler_addrs, uint64_t kv_id,
             uint64_t ctrl_id)
      : sim_(sim),
        ctrl_ck_(std::make_shared<CtrlerClerk>(sim, std::move(ctrler_addrs),
                                               ctrl_id)),
        id_(kv_id) {}

  // The verbs hand the coroutine a shared self: a spawned op must keep the
  // clerk alive even if the task that created it is aborted mid-await (the
  // reference gets this for free from Rust ownership; C++ member coroutines
  // capture a raw `this`).
  Task<std::string> get(std::string key) {
    return call(shared_from_this(), Op{Op::Kind::Get, std::move(key), {}});
  }
  Task<std::string> put(std::string key, std::string value) {
    return call(shared_from_this(),
                Op{Op::Kind::Put, std::move(key), std::move(value)});
  }
  Task<std::string> append(std::string key, std::string value) {
    return call(shared_from_this(),
                Op{Op::Kind::Append, std::move(key), std::move(value)});
  }
  uint64_t id() const { return id_; }

 private:
  static Task<std::string> call(std::shared_ptr<ShardClerk> self, Op op) {
    uint64_t seq = ++self->seq_;
    size_t shard = key2shard(op.key);
    for (;;) {
      if (self->config_.num == 0)
        self->config_ = co_await self->ctrl_ck_->query();
      Gid g = self->config_.shards[shard];
      auto git = self->config_.groups.find(g);
      if (g != 0 && git != self->config_.groups.end() &&
          !git->second.empty()) {
        // copy, not reference: this loop reassigns config_ (bottom of the
        // outer loop) while iterating — and a contract-violating caller
        // running sibling ops must corrupt results, not memory
        std::vector<Addr> servers = git->second;
        size_t i = self->leader_[g] % servers.size();
        bool wrong_group = false;
        for (size_t tries = 0; tries < servers.size() + 2 && !wrong_group;
             tries++) {
          auto reply = co_await self->sim_->call_timeout(
              servers[i], KvRequest{self->id_, seq, op}, 500 * MSEC);
          if (reply && reply->code == Code::Ok) {
            self->leader_[g] = i;
            co_return reply->value;
          }
          if (reply && reply->code == Code::WrongGroup) {
            // rotate the cached leader before re-querying: a deposed leader
            // with a stale config would otherwise answer WrongGroup forever
            // while the group's live majority is never tried
            self->leader_[g] = (i + 1) % servers.size();
            wrong_group = true;
          } else if (reply && reply->code == Code::NotLeader &&
                     reply->hint >= 0 && size_t(reply->hint) < servers.size() &&
                     size_t(reply->hint) != i) {
            i = size_t(reply->hint);
          } else {
            i = (i + 1) % servers.size();
          }
        }
      }
      co_await self->sim_->sleep(100 * MSEC);
      self->config_ = co_await self->ctrl_ck_->query();  // refresh, re-route
    }
  }

  Sim* sim_;
  std::shared_ptr<CtrlerClerk> ctrl_ck_;
  uint64_t id_;
  uint64_t seq_ = 0;
  Config config_;
  std::map<Gid, size_t> leader_;  // per-group leader hint
};

}  // namespace shardkv
