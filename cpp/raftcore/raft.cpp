#include "raft.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>

namespace raftcore {

// Deliberate-bug knob for the TPU<->simcore differential bridge: forcing the
// quorum size below a real majority (e.g. 2 on a 5-node cluster) lets two
// candidates win the same term, which the safety oracles must catch. Mirrors
// the TPU backend's SimConfig.majority_override (madraft_tpu/tpusim/config.py)
// so a violation class found by the batched fuzzer replays here.
static size_t quorum(size_t n_peers) {
  // read per call, NOT cached statically: the in-process C API
  // (cpp/tools/capi.cpp) runs many replays with different overrides in one
  // process; getenv is cheap relative to a commit advance
  const char* e = std::getenv("MADTPU_MAJORITY_OVERRIDE");
  int override_v = e ? std::atoi(e) : 0;
  // clamp: an override above the cluster size would wrap the
  // peers_.size() - quorum() index in advance_commit
  return override_v > 0 ? std::min((size_t)override_v, n_peers)
                        : n_peers / 2 + 1;
}

// The planted-bug library (mirrors the TPU backend's SimConfig.bug /
// config.py RAFT_BUGS): MADTPU_BUG names one classic Raft implementation
// bug to inject, so a violation class the batched fuzzer finds under a bug
// replays here with the same bug for differential cross-validation. Read
// per call for the same reason quorum() is.
static bool bug(const char* name) {
  const char* e = std::getenv("MADTPU_BUG");
  return e && !std::strcmp(e, name);
}

// ------------------------------------------------------------------- boot

Task<std::shared_ptr<Raft>> Raft::boot(Sim* sim, std::vector<Addr> peers,
                                       size_t me, Channel<ApplyMsg> apply_ch) {
  auto rf = std::shared_ptr<Raft>(
      new Raft(sim, std::move(peers), me, std::move(apply_ch)));
  rf->next_idx_.assign(rf->peers_.size(), 1);
  rf->match_idx_.assign(rf->peers_.size(), 0);
  rf->sent_commit_.assign(rf->peers_.size(), 0);
  rf->restore();
  // Deliver the restored snapshot to the service before any command
  // (the apply channel is FIFO, so the service sees it first — the
  // reference's restore() path, raft.rs:194-211).
  if (rf->snap_last_index_ > 0) {
    rf->apply_ch_.send(ApplyMsg{true, rf->snap_data_, rf->snap_last_index_,
                                rf->snap_last_term_});
    rf->commit_ = rf->snap_last_index_;
    rf->last_applied_ = rf->snap_last_index_;
  }
  rf->register_handlers();
  rf->reset_election_deadline();
  sim->spawn(rf->addr_, election_loop(rf));
  co_return rf;
}

// ---------------------------------------------------------------- handlers

namespace {
// Handler coroutines are free functions taking the shared_ptr by value: the
// coroutine frame owns its own reference, so a handler re-registration (which
// destroys the capturing closure) can never dangle a running handler.
Task<RequestVoteReply> rv_handler(std::shared_ptr<Raft> rf,
                                  RequestVoteArgs a,
                                  RequestVoteReply (Raft::*fn)(const RequestVoteArgs&)) {
  co_return(rf.get()->*fn)(a);
}
Task<AppendEntriesReply> ae_handler(
    std::shared_ptr<Raft> rf, AppendEntriesArgs a,
    AppendEntriesReply (Raft::*fn)(const AppendEntriesArgs&)) {
  co_return(rf.get()->*fn)(a);
}
Task<InstallSnapshotReply> is_handler(
    std::shared_ptr<Raft> rf, InstallSnapshotArgs a,
    InstallSnapshotReply (Raft::*fn)(const InstallSnapshotArgs&)) {
  co_return(rf.get()->*fn)(a);
}
}  // namespace

void Raft::register_handlers() {
  // net.add_rpc_handler pattern (raft.rs:213-222); runs in boot's node context
  auto self = shared_from_this();
  sim_->add_rpc_handler<RequestVoteArgs>(
      [self](RequestVoteArgs a) -> Task<RequestVoteReply> {
        return rv_handler(self, std::move(a), &Raft::handle_request_vote);
      });
  sim_->add_rpc_handler<AppendEntriesArgs>(
      [self](AppendEntriesArgs a) -> Task<AppendEntriesReply> {
        return ae_handler(self, std::move(a), &Raft::handle_append_entries);
      });
  sim_->add_rpc_handler<InstallSnapshotArgs>(
      [self](InstallSnapshotArgs a) -> Task<InstallSnapshotReply> {
        return is_handler(self, std::move(a), &Raft::handle_install_snapshot);
      });
}

RequestVoteReply Raft::handle_request_vote(const RequestVoteArgs& a) {
  uint64_t term0 = term_;
  int voted0 = voted_for_;
  if (a.term > term_) step_down(a.term);
  bool grant = false;
  if (a.term == term_ && (voted_for_ == -1 || voted_for_ == (int)a.candidate)) {
    // election restriction (§5.4.1): candidate's log at least as up-to-date
    uint64_t my_llt = term_at(last_index());
    if (bug("grant_any_vote") || a.last_log_term > my_llt ||
        (a.last_log_term == my_llt && a.last_log_index >= last_index())) {
      grant = true;
      voted_for_ = (int)a.candidate;
      reset_election_deadline();
    }
  }
  // planted bug (config.py RAFT_BUGS): reply from VOLATILE state — the
  // persist-before-reply fsync is skipped, so a kill/restore rolls the
  // vote (and term) back to whatever the last unrelated persist() wrote
  // and the voter can re-grant the term. Mirrors the TPU backend's
  // ack_before_fsync handler-sync strip (step.py).
  if ((term_ != term0 || voted_for_ != voted0) && !bug("ack_before_fsync"))
    persist();  // before the reply leaves the node (raft.rs:224-233)
  return {term_, grant};
}

AppendEntriesReply Raft::handle_append_entries(const AppendEntriesArgs& a) {
  if (a.term < term_) return {term_, false, 0};
  uint64_t term0 = term_;
  bool log_dirty = false;
  if (a.term > term_) step_down(a.term);
  if (role_ == Role::Candidate) role_ = Role::Follower;
  leader_hint_ = (int)a.leader;
  reset_election_deadline();

  // planted bug (config.py RAFT_BUGS): every persist in this handler is
  // skipped — the follower acks appended entries from volatile state, so a
  // kill/restore rolls its log back past entries a leader already
  // commit-counted. Mirrors the TPU ack_before_fsync (step.py).
  const bool ack_bug = bug("ack_before_fsync");
  uint64_t prev_index = a.prev_index;
  size_t skip = 0;  // entries already covered by our snapshot
  if (prev_index < snap_last_index_) {
    // stale retransmit reaching into our compacted prefix: everything up to
    // the snapshot is committed, so just skip that part of the batch
    skip = std::min<uint64_t>(snap_last_index_ - prev_index, a.entries.size());
    prev_index = snap_last_index_;
  }
  if (prev_index > last_index()) {
    if (term_ != term0 && !ack_bug) persist();
    return {term_, false, last_index() + 1};
  }
  if (term_at(prev_index) != a.prev_term && prev_index > snap_last_index_) {
    // fast backtrack: first index of the conflicting term
    uint64_t ct = term_at(prev_index);
    uint64_t first = prev_index;
    while (first - 1 > snap_last_index_ && term_at(first - 1) == ct) first--;
    if (term_ != term0 && !ack_bug) persist();
    return {term_, false, first};
  }
  // append, truncating at the first conflict (never truncate on a match —
  // a delayed short AE must not drop entries a newer one appended)
  uint64_t idx = prev_index;
  const bool no_trunc = bug("no_truncate");  // hoisted: one env read per call
  for (size_t k = skip; k < a.entries.size(); k++) {
    idx = prev_index + (k - skip) + 1;
    if (idx <= last_index()) {
      if (term_at(idx) != a.entries[k].term && !no_trunc) {
        log_.resize(idx - snap_last_index_ - 1);
        log_.push_back(a.entries[k]);
        log_dirty = true;
      }
    } else {
      log_.push_back(a.entries[k]);
      log_dirty = true;
    }
  }
  uint64_t last_new = prev_index + (a.entries.size() - skip);
  if (a.leader_commit > commit_) {
    commit_ = std::min(a.leader_commit, std::max(last_new, commit_));
    commit_ = std::min(commit_, last_index());
  }
  if ((term_ != term0 || log_dirty) && !ack_bug) persist();
  apply_committed();
  return {term_, true, last_new};
}

InstallSnapshotReply Raft::handle_install_snapshot(const InstallSnapshotArgs& a) {
  if (a.term < term_) return {term_};
  uint64_t term0 = term_;
  if (a.term > term_) step_down(a.term);
  if (role_ == Role::Candidate) role_ = Role::Follower;
  leader_hint_ = (int)a.leader;
  reset_election_deadline();
  if (term_ != term0) persist();
  // ignore snapshots that would regress the service's applied state
  // (reorderings/retransmits on an unreliable net)
  if (a.last_index <= last_applied_ || a.last_index <= snap_last_index_)
    return {term_};
  // hand to the service; it answers via cond_install_snapshot (raft.rs:149-168)
  apply_ch_.send(ApplyMsg{true, a.data, a.last_index, a.last_term});
  return {term_};
}

bool Raft::cond_install_snapshot(uint64_t last_term, uint64_t last_index,
                                 Bytes data) {
  if (last_index < snap_last_index_ || last_index < last_applied_) return false;
  // keep our log suffix if it extends past the snapshot and matches its term
  if (last_index <= this->last_index() && term_at(last_index) == last_term) {
    log_.erase(log_.begin(),
               log_.begin() + (last_index - snap_last_index_));
  } else {
    log_.clear();
  }
  MT_LOG("raft", "node %zu installs snapshot through index %llu", me_,
         (unsigned long long)last_index);
  snap_last_index_ = last_index;
  snap_last_term_ = last_term;
  snap_data_ = std::move(data);
  snap_dirty_ = true;
  commit_ = std::max(commit_, last_index);
  last_applied_ = std::max(last_applied_, last_index);
  persist();
  return true;
}

void Raft::snapshot(uint64_t index, Bytes data) {
  // service-triggered compaction (raft.rs:166); index is always <= applied
  if (index <= snap_last_index_) return;
  uint64_t t = term_at(index);
  log_.erase(log_.begin(), log_.begin() + (index - snap_last_index_));
  snap_last_index_ = index;
  snap_last_term_ = t;
  snap_data_ = std::move(data);
  snap_dirty_ = true;
  persist();
}

// ----------------------------------------------------------------- election

Task<void> Raft::election_loop(std::shared_ptr<Raft> self) {
  for (;;) {
    co_await self->sim_->sleep(TICK);
    if (self->role_ != Role::Leader &&
        self->sim_->now() >= self->election_deadline_) {
      self->start_election();
    }
  }
}

void Raft::start_election() {
  term_++;
  MT_LOG("raft", "node %zu starts election for term %llu", me_,
         (unsigned long long)term_);
  role_ = Role::Candidate;
  voted_for_ = (int)me_;
  votes_ = 1;
  reset_election_deadline();
  persist();  // before any RequestVote leaves (raft.rs:224-233)
  auto self = shared_from_this();
  for (size_t p = 0; p < peers_.size(); p++) {
    if (p == me_) continue;
    sim_->spawn(addr_, vote_task(self, peers_[p], term_));
  }
}

Task<void> Raft::vote_task(std::shared_ptr<Raft> self, Addr peer,
                           uint64_t term) {
  RequestVoteArgs args{term, (uint32_t)self->me_, self->last_index(),
                       self->term_at(self->last_index())};
  auto r = co_await self->sim_->call_timeout(peer, args, RPC_TIMEOUT);
  if (!r) co_return;
  if (r->term > self->term_) {
    self->step_down(r->term);
    self->persist();
    co_return;
  }
  if (self->role_ == Role::Candidate && self->term_ == term && r->granted) {
    self->votes_++;
    if (self->votes_ >= quorum(self->peers_.size())) self->become_leader();
  }
}

void Raft::become_leader() {
  MT_LOG("raft", "node %zu becomes leader of term %llu (log %llu)", me_,
         (unsigned long long)term_, (unsigned long long)last_index());
  role_ = Role::Leader;
  leader_hint_ = (int)me_;
  for (size_t p = 0; p < peers_.size(); p++) {
    next_idx_[p] = last_index() + 1;
    match_idx_[p] = 0;
    sent_commit_[p] = 0;  // forces an immediate announce-AE per peer
  }
  auto self = shared_from_this();
  for (size_t p = 0; p < peers_.size(); p++) {
    if (p == me_) continue;
    sim_->spawn(addr_, replicator(self, p, term_));
  }
}

void Raft::step_down(uint64_t new_term) {
  // NOTE: does not touch the election deadline — the timer resets only on
  // granting a vote or hearing from the current-term leader (Raft §5.2);
  // resetting here would let an unelectable high-term disrupter postpone
  // re-election indefinitely.
  MT_LOG("raft", "node %zu steps down to term %llu", me_,
         (unsigned long long)new_term);
  term_ = new_term;
  role_ = Role::Follower;
  voted_for_ = -1;
}

void Raft::reset_election_deadline() {
  election_deadline_ =
      sim_->now() + sim_->rand_range(ELECTION_MIN, ELECTION_MAX + 1);
}

// -------------------------------------------------------------- replication

StartResult Raft::start(Bytes cmd) {
  if (role_ != Role::Leader) return {false, 0, 0, leader_hint_};
  log_.push_back(LogEntry{term_, std::move(cmd)});
  persist();
  advance_commit();  // single-node cluster commits immediately
  return {true, last_index(), term_, (int)me_};
}

Task<void> Raft::replicator(std::shared_ptr<Raft> self, size_t p,
                            uint64_t term) {
  Addr peer = self->peers_[p];
  uint64_t last_send = 0;
  bool first = true;
  while (self->role_ == Role::Leader && self->term_ == term) {
    Sim* sim = self->sim_;
    bool due = sim->now() >= last_send + HEARTBEAT;
    bool fresh = self->last_index() >= self->next_idx_[p] ||
                 self->commit_ > self->sent_commit_[p];
    if (!(first || due || fresh)) {
      co_await sim->sleep(POLL);
      continue;
    }
    first = false;
    last_send = sim->now();
    if (self->next_idx_[p] <= self->snap_last_index_) {
      // peer is behind our compaction horizon -> InstallSnapshot (raft.rs:159)
      InstallSnapshotArgs args{term, (uint32_t)self->me_,
                               self->snap_last_index_, self->snap_last_term_,
                               self->snap_data_};
      auto r = co_await sim->call_timeout(peer, args, RPC_TIMEOUT);
      if (self->role_ != Role::Leader || self->term_ != term) co_return;
      if (!r) continue;
      if (r->term > self->term_) {
        self->step_down(r->term);
        self->persist();
        co_return;
      }
      self->match_idx_[p] = std::max(self->match_idx_[p], args.last_index);
      self->next_idx_[p] = std::max(self->next_idx_[p], args.last_index + 1);
      continue;
    }
    AppendEntriesArgs args;
    args.term = term;
    args.leader = (uint32_t)self->me_;
    args.prev_index = self->next_idx_[p] - 1;
    args.prev_term = self->term_at(args.prev_index);
    uint64_t from = self->next_idx_[p];
    uint64_t upto =
        std::min(self->last_index(), from + (AE_BATCH_MAX - 1));
    for (uint64_t i = from; i <= upto; i++)
      args.entries.push_back(self->entry_at(i));
    args.leader_commit = self->commit_;
    self->sent_commit_[p] = self->commit_;
    auto r = co_await sim->call_timeout(peer, args, RPC_TIMEOUT);
    if (self->role_ != Role::Leader || self->term_ != term) co_return;
    if (!r) continue;  // lost/timeout: next loop retries (heartbeat due)
    if (r->term > self->term_) {
      self->step_down(r->term);
      self->persist();
      co_return;
    }
    if (r->success) {
      self->match_idx_[p] = std::max(self->match_idx_[p], r->hint);
      self->next_idx_[p] = std::max(self->next_idx_[p], r->hint + 1);
      self->advance_commit();
    } else {
      // fast backtrack to the follower's hint; floor at 1 (snapshot case is
      // handled by the next_idx_ <= snap_last_index_ branch next round)
      self->next_idx_[p] =
          std::max<uint64_t>(1, std::min(self->next_idx_[p], r->hint));
    }
  }
}

void Raft::advance_commit() {
  if (role_ != Role::Leader) return;
  std::vector<uint64_t> m = match_idx_;
  m[me_] = last_index();
  std::sort(m.begin(), m.end());
  uint64_t majority_match = m[peers_.size() - quorum(peers_.size())];
  // only commit entries from the current term (Raft §5.4.2, Figure 8)
  if (majority_match > commit_ && majority_match > snap_last_index_ &&
      (term_at(majority_match) == term_ || bug("commit_any_term"))) {
    MT_LOG("raft", "leader %zu advances commit %llu -> %llu", me_,
           (unsigned long long)commit_, (unsigned long long)majority_match);
    commit_ = majority_match;
    apply_committed();
  }
}

void Raft::apply_committed() {
  while (last_applied_ < commit_) {
    last_applied_++;
    if (last_applied_ <= snap_last_index_) continue;  // covered by snapshot
    apply_ch_.send(
        ApplyMsg{false, entry_at(last_applied_).data, last_applied_, 0});
  }
}

// -------------------------------------------------------------- persistence

uint64_t Raft::term_at(uint64_t index) const {
  if (index == snap_last_index_) return snap_last_term_;
  if (index == 0) return 0;
  return log_[index - snap_last_index_ - 1].term;
}

void Raft::persist() {
  // "state" = Persist{term, voted_for, snapshot meta, log}; "snapshot" = raw
  // service bytes. Both synced per write — the file-size contract the testers
  // assert on (raft.rs:173-211, tester.rs:152-158).
  Enc e;
  e.u64(term_);
  e.u64((uint64_t)(voted_for_ + 1));
  e.u64(snap_last_index_);
  e.u64(snap_last_term_);
  e.u64(log_.size());
  for (auto& ent : log_) {
    e.u64(ent.term);
    e.bytes(ent.data);
  }
  sim_->fs_write_at(addr_, "state", std::move(e.out));
  if (snap_dirty_) {
    sim_->fs_write_at(addr_, "snapshot", snap_data_);
    snap_dirty_ = false;
  }
}

void Raft::restore() {
  auto snap = sim_->fs_read_at(addr_, "snapshot");
  if (snap) snap_data_ = *snap;
  auto st = sim_->fs_read_at(addr_, "state");
  if (!st) return;  // first boot (NotFound, raft.rs:195-209)
  Dec d(*st);
  term_ = d.u64();
  voted_for_ = (int)d.u64() - 1;
  // planted bug (config.py RAFT_BUGS): votedFor "not persisted" — modeled
  // at restore so the persist()-side file contract stays byte-identical
  if (bug("forget_voted_for")) voted_for_ = -1;
  snap_last_index_ = d.u64();
  snap_last_term_ = d.u64();
  uint64_t n = d.u64();
  log_.clear();
  for (uint64_t i = 0; i < n; i++) {
    LogEntry ent;
    ent.term = d.u64();
    ent.data = d.bytes();
    log_.push_back(std::move(ent));
  }
}

}  // namespace raftcore
