// raft-core — a complete Raft consensus implementation on the simcore
// deterministic runtime. This fills in what the reference leaves as todo!()
// stubs while matching its public API surface (SURVEY.md §2 C2-C4):
//
//   RaftHandle::new(peers, me) -> (handle, apply channel)
//     -> Raft::boot(sim, peers, me, apply_ch)    (/root/reference/src/raft/raft.rs:108)
//   start(&[u8]) -> Result<Start{index,term}, NotLeader(hint)>
//     -> Raft::start(Bytes) -> StartResult        (raft.rs:131, raft.rs:40-53)
//   term() / is_leader()                          (raft.rs:138,144)
//   snapshot(index, &[u8])                        (raft.rs:166)
//   cond_install_snapshot(term, index, &[u8])     (raft.rs:153)
//   ApplyMsg::{Command, Snapshot}                 (raft.rs:26-37)
//   persistence files "state"/"snapshot"          (raft.rs:173-211)
//
// Design notes (deliberately not a port):
//  * simcore is single-threaded, so there are no locks; every mutation runs
//    to completion between awaits.
//  * Persistence (fs_write) is synchronous in-sim, which gives the
//    "persist before reply/send" ordering of the reference (raft.rs:224-233)
//    simply by calling persist() before any co_return / RPC send.
//  * Replication uses one long-lived coroutine per (leader-term, peer) that
//    sends when there is new data (entries or commit) or a heartbeat is due,
//    otherwise polls virtual time; polling costs nothing in a discrete-event
//    simulator and keeps RPC counts within the reference budgets
//    (/root/reference/src/raft/tests.rs:389-479).
#pragma once

#include <cstdint>
#include <memory>
#include <vector>

#include "../simcore/simcore.h"
#include "codec.h"

namespace raftcore {

using simcore::Addr;
using simcore::Channel;
using simcore::Sim;
using simcore::Task;
using simcore::MSEC;

struct LogEntry {
  uint64_t term;
  Bytes data;
};

// raft.rs:26-37
struct ApplyMsg {
  bool is_snapshot;
  Bytes data;
  uint64_t index;  // command index, or snapshot last-included index
  uint64_t term;   // snapshot only
};

// raft.rs:40-53: Start{index,term} or NotLeader(hint)
struct StartResult {
  bool ok;
  uint64_t index = 0;
  uint64_t term = 0;
  int hint = -1;  // last observed leader id; -1 unknown
};

struct RequestVoteReply {
  uint64_t term;
  bool granted;
};
struct RequestVoteArgs {
  uint64_t term;
  uint32_t candidate;  // peer id (index into peers)
  uint64_t last_log_index;
  uint64_t last_log_term;
  using Reply = RequestVoteReply;
};

struct AppendEntriesReply {
  uint64_t term;
  bool success;
  uint64_t hint;  // on failure: next index the leader should try (fast backtrack)
};
struct AppendEntriesArgs {
  uint64_t term;
  uint32_t leader;
  uint64_t prev_index;
  uint64_t prev_term;
  std::vector<LogEntry> entries;
  uint64_t leader_commit;
  using Reply = AppendEntriesReply;
};

struct InstallSnapshotReply {
  uint64_t term;
};
struct InstallSnapshotArgs {
  uint64_t term;
  uint32_t leader;
  uint64_t last_index;
  uint64_t last_term;
  Bytes data;
  using Reply = InstallSnapshotReply;
};

class Raft : public std::enable_shared_from_this<Raft> {
 public:
  // Boot a node: restore from its persistent files, register RPC handlers,
  // start the election ticker. MUST be spawned on peers[me]'s address (the
  // reference boots via local_handle(addr).spawn(RaftHandle::new),
  // tester.rs:297-298). If a snapshot was restored, it is delivered first on
  // the apply channel so the service can reinstall its state.
  static Task<std::shared_ptr<Raft>> boot(Sim* sim, std::vector<Addr> peers,
                                          size_t me, Channel<ApplyMsg> apply_ch);

  // Submit a command; leader-only. Appends + persists synchronously; the
  // replicators pick it up on their next poll (<= POLL virtual time later).
  StartResult start(Bytes cmd);

  uint64_t term() const { return term_; }
  bool is_leader() const { return role_ == Role::Leader; }
  int leader_hint() const { return leader_hint_; }

  // Service-driven log compaction (raft.rs:166): everything <= index is
  // covered by `data`.
  void snapshot(uint64_t index, Bytes data);

  // Apply-channel handshake for leader-installed snapshots (raft.rs:153).
  bool cond_install_snapshot(uint64_t last_term, uint64_t last_index, Bytes data);

  // --- introspection for testers ---
  uint64_t last_index() const { return snap_last_index_ + log_.size(); }
  uint64_t commit_index() const { return commit_; }

  // timing constants (virtual ns)
  static constexpr uint64_t TICK = 10 * MSEC;       // election ticker period
  static constexpr uint64_t POLL = 5 * MSEC;        // replicator poll period
  static constexpr uint64_t HEARTBEAT = 100 * MSEC; // idle AE cadence
  static constexpr uint64_t RPC_TIMEOUT = 100 * MSEC;
  static constexpr uint64_t ELECTION_MIN = 150 * MSEC;  // raft.rs:262
  static constexpr uint64_t ELECTION_MAX = 300 * MSEC;
  static constexpr size_t AE_BATCH_MAX = 128;  // entries per AppendEntries

 private:
  enum class Role { Follower, Candidate, Leader };

  Raft(Sim* sim, std::vector<Addr> peers, size_t me, Channel<ApplyMsg> ch)
      : sim_(sim), peers_(std::move(peers)), me_(me), addr_(peers_[me]),
        apply_ch_(std::move(ch)) {}

  // RPC handlers (synchronous; persist before returning the reply)
  RequestVoteReply handle_request_vote(const RequestVoteArgs& a);
  AppendEntriesReply handle_append_entries(const AppendEntriesArgs& a);
  InstallSnapshotReply handle_install_snapshot(const InstallSnapshotArgs& a);

  // long-lived tasks (spawned on addr_, so Sim::kill crashes them)
  static Task<void> election_loop(std::shared_ptr<Raft> self);
  static Task<void> vote_task(std::shared_ptr<Raft> self, Addr peer,
                              uint64_t term);
  static Task<void> replicator(std::shared_ptr<Raft> self, size_t peer,
                               uint64_t term);

  void start_election();
  void become_leader();
  void step_down(uint64_t new_term);  // caller persists
  void reset_election_deadline();
  void advance_commit();
  void apply_committed();
  void register_handlers();

  // log index mapping: log_[k] holds index snap_last_index_ + 1 + k (1-based)
  uint64_t term_at(uint64_t index) const;
  const LogEntry& entry_at(uint64_t index) const {
    return log_[index - snap_last_index_ - 1];
  }

  void persist();
  void restore();

  Sim* sim_;
  std::vector<Addr> peers_;
  size_t me_;
  Addr addr_;
  Channel<ApplyMsg> apply_ch_;

  // persistent (raft.rs:95-98 Persist{term, voted_for, log} + snapshot meta)
  uint64_t term_ = 0;
  int voted_for_ = -1;
  std::vector<LogEntry> log_;
  uint64_t snap_last_index_ = 0;
  uint64_t snap_last_term_ = 0;
  Bytes snap_data_;
  bool snap_dirty_ = false;  // write the "snapshot" file only when it changed

  // volatile
  Role role_ = Role::Follower;
  uint64_t commit_ = 0;
  uint64_t last_applied_ = 0;
  uint64_t election_deadline_ = 0;
  int leader_hint_ = -1;
  size_t votes_ = 0;
  std::vector<uint64_t> next_idx_;
  std::vector<uint64_t> match_idx_;
  std::vector<uint64_t> sent_commit_;  // commit index last sent to each peer
};

}  // namespace raftcore
