// Tiny deterministic binary codec for persistence and snapshots.
//
// The reference encodes its persistent state and wire values with bincode
// (/root/reference/src/raft/raft.rs:176,204); in-process RPC payloads here are
// typed C++ values (serialization is semantically irrelevant in-sim, see
// simcore.h), so this codec exists only for the on-"disk" byte contract:
// the "state"/"snapshot" files whose sizes the testers assert on
// (/root/reference/src/raft/tester.rs:152-158).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace raftcore {

using Bytes = std::vector<uint8_t>;

struct Enc {
  Bytes out;
  void u64(uint64_t v) {
    for (int i = 0; i < 8; i++) out.push_back(uint8_t(v >> (8 * i)));
  }
  void bytes(const Bytes& b) {
    u64(b.size());
    out.insert(out.end(), b.begin(), b.end());
  }
  void str(const std::string& s) {
    u64(s.size());
    out.insert(out.end(), s.begin(), s.end());
  }
};

struct Dec {
  const Bytes* in;
  size_t pos = 0;
  explicit Dec(const Bytes& b) : in(&b) {}
  uint64_t u64() {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v |= uint64_t((*in)[pos++]) << (8 * i);
    return v;
  }
  Bytes bytes() {
    size_t n = u64();
    Bytes b(in->begin() + pos, in->begin() + pos + n);
    pos += n;
    return b;
  }
  std::string str() {
    size_t n = u64();
    std::string s(in->begin() + pos, in->begin() + pos + n);
    pos += n;
    return s;
  }
  bool done() const { return pos >= in->size(); }
};

inline Bytes enc_u64(uint64_t v) {
  Enc e;
  e.u64(v);
  return e.out;
}
inline uint64_t dec_u64(const Bytes& b) {
  Dec d(b);
  return d.u64();
}

}  // namespace raftcore
