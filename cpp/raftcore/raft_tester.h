// RaftTester — cluster harness + safety/liveness checkers for the Lab 2
// suite, the C++ analogue of the reference's tester (SURVEY.md §2 C10,
// /root/reference/src/raft/tester.rs):
//   * n nodes at addresses 0.0.1.i (tester.rs:46-48)
//   * per-node applier feeding shared storage with online safety checks
//     (committed-value agreement + in-order apply, tester.rs:301-326,379-397)
//   * liveness driver one(cmd, expected, retry) with 10s/2s budgets
//     (tester.rs:216-262)
//   * fault verbs: connect/disconnect, crash1 (kill), start1 (restart with
//     recovery) (tester.rs:264-333)
//   * unreliable-net toggle: 10% loss, 1-27ms latency (tester.rs:127-137)
//   * metrics: RPC count = msg_count/2, on-disk log/snapshot size
//     (tester.rs:147-158)
//   * SNAPSHOT_INTERVAL=10: applier snapshots every 10th index when enabled
//     (tester.rs:31,311-313)
#pragma once

#include <cstdio>
#include <map>
#include <memory>
#include <optional>
#include <vector>

#include "../tests/framework.h"
#include "raft.h"

namespace raftcore {

using simcore::SEC;
using simcore::make_addr;

constexpr uint64_t RAFT_ELECTION_TIMEOUT = 1 * SEC;  // tests.rs:18
constexpr uint64_t SNAPSHOT_INTERVAL = 10;           // tester.rs:31

class RaftTester {
 public:
  RaftTester(Sim* sim, int n, bool unreliable, bool snapshot)
      : sim_(sim), n_(n), snapshot_(snapshot) {
    for (int i = 0; i < n; i++) addrs_.push_back(make_addr(0, 0, 1, i + 1));
    rafts_.resize(n);
    connected_.assign(n, false);
    storage_.resize(n);
    set_unreliable(unreliable);
    start_time_ = sim->now();
  }

  Task<void> init() {
    for (int i = 0; i < n_; i++) {
      co_await sim_->spawn(start1(i));
      connect(i);
    }
  }

  Sim* sim() { return sim_; }
  int n() const { return n_; }
  std::shared_ptr<Raft> raft(int i) { return rafts_[i]; }

  // ---- cluster control (tester.rs:264-333)
  Task<void> start1(int i) {
    crash1(i);
    Channel<ApplyMsg> ch;
    rafts_[i] = co_await sim_->spawn(addrs_[i],
                                     Raft::boot(sim_, addrs_, i, ch));
    sim_->spawn(addrs_[i], applier_task(this, i, ch));
  }
  void crash1(int i) {
    sim_->kill(addrs_[i]);
    rafts_[i] = nullptr;
  }
  void connect(int i) {
    connected_[i] = true;
    sim_->connect(addrs_[i]);
  }
  void disconnect(int i) {
    connected_[i] = false;
    sim_->disconnect(addrs_[i]);
  }
  bool is_connected(int i) const { return connected_[i]; }

  void set_unreliable(bool unreliable) {
    auto& cfg = sim_->net_config();
    if (unreliable) {
      cfg.packet_loss_rate = 0.1;
      cfg.send_latency_min = 1 * MSEC;   // tester.rs:127-137
      cfg.send_latency_max = 27 * MSEC;
    } else {
      cfg.packet_loss_rate = 0.0;
      cfg.send_latency_min = 1 * MSEC;
      cfg.send_latency_max = 10 * MSEC;
    }
  }

  // ---- metrics (tester.rs:147-158)
  uint64_t rpcs() const { return sim_->msg_count() / 2; }
  size_t log_size() const {
    size_t m = 0;
    for (auto a : addrs_) m = std::max(m, sim_->fs_size(a, "state"));
    return m;
  }
  size_t snapshot_size() const {
    size_t m = 0;
    for (auto a : addrs_) m = std::max(m, sim_->fs_size(a, "snapshot"));
    return m;
  }

  // ---- checkers (tester.rs:64-109)
  Task<int> check_one_leader() {
    for (int iters = 0; iters < 10; iters++) {
      co_await sim_->sleep(sim_->rand_range(450, 551) * MSEC);
      std::map<uint64_t, std::vector<int>> leaders;
      for (int i = 0; i < n_; i++)
        if (connected_[i] && rafts_[i] && rafts_[i]->is_leader())
          leaders[rafts_[i]->term()].push_back(i);
      for (auto& [term, who] : leaders) {
        if (who.size() > 1) {
          std::fprintf(stderr, "term %llu has %zu (>1) leaders\n",
                       (unsigned long long)term, who.size());
          std::abort();
        }
      }
      if (!leaders.empty()) co_return leaders.rbegin()->second[0];
    }
    std::fprintf(stderr, "expected one leader, got none\n");
    std::abort();
  }

  Task<void> check_no_leader() {
    for (int i = 0; i < n_; i++) {
      if (connected_[i] && rafts_[i] && rafts_[i]->is_leader()) {
        std::fprintf(stderr, "expected no leader, but %d claims to be\n", i);
        std::abort();
      }
    }
    co_return;
  }

  Task<uint64_t> check_terms() {
    uint64_t term = 0;
    for (int i = 0; i < n_; i++) {
      if (connected_[i] && rafts_[i]) {
        uint64_t t = rafts_[i]->term();
        if (term == 0) term = t;
        else if (term != t) {
          std::fprintf(stderr, "servers disagree on term\n");
          std::abort();
        }
      }
    }
    co_return term;
  }

  // how many peers have committed (applied) `index`, and the value there
  std::pair<int, std::optional<uint64_t>> n_committed(uint64_t index) {
    int count = 0;
    std::optional<uint64_t> val;
    for (int i = 0; i < n_; i++) {
      if (storage_[i].size() >= index) {
        count++;
        val = storage_[i][index - 1];  // agreement already checked on apply
      }
    }
    return {count, val};
  }

  // wait for index to be committed by at least n peers; nullopt if term moved
  Task<std::optional<uint64_t>> wait(uint64_t index, int n, uint64_t term) {
    uint64_t to = 10 * MSEC;
    for (int iters = 0; iters < 30; iters++) {
      auto [nd, val] = n_committed(index);
      if (nd >= n) co_return val;
      co_await sim_->sleep(to);
      if (to < 1 * SEC) to *= 2;
      for (int i = 0; i < n_; i++)
        if (rafts_[i] && rafts_[i]->term() > term) co_return std::nullopt;
    }
    auto [nd, val] = n_committed(index);
    if (nd < n) {
      std::fprintf(stderr, "only %d decided for index %llu; wanted %d\n", nd,
                   (unsigned long long)index, n);
      std::abort();
    }
    co_return val;
  }

  // liveness driver (tester.rs:216-262): submit cmd, require `expected`
  // servers to commit it; 10s total / 2s per-index budget (virtual time)
  Task<uint64_t> one(uint64_t cmd, int expected, bool retry) {
    uint64_t t0 = sim_->now();
    int probe = 0;
    while (sim_->now() - t0 < 10 * SEC) {
      std::optional<uint64_t> index;
      for (int off = 0; off < n_; off++) {
        probe = (probe + 1) % n_;
        if (!connected_[probe] || !rafts_[probe]) continue;
        auto r = rafts_[probe]->start(enc_u64(cmd));
        if (r.ok) {
          index = r.index;
          break;
        }
      }
      if (index) {
        uint64_t t1 = sim_->now();
        while (sim_->now() - t1 < 2 * SEC) {
          auto [nd, val] = n_committed(*index);
          if (nd >= expected && val && *val == cmd) co_return *index;
          co_await sim_->sleep(20 * MSEC);
        }
        if (!retry) break;
      } else {
        co_await sim_->sleep(50 * MSEC);
      }
    }
    std::fprintf(stderr, "one(%llu) failed to reach agreement\n",
                 (unsigned long long)cmd);
    std::abort();
  }

  // per-test perf summary (tester.rs:339-351)
  void end() {
    std::printf("  ... elapsed %.2fs(virt) peers %d rpcs %llu commits %zu\n",
                (sim_->now() - start_time_) / 1e9, n_,
                (unsigned long long)rpcs(), max_applied());
  }

  size_t max_applied() const {
    size_t m = 0;
    for (auto& s : storage_) m = std::max(m, s.size());
    return m;
  }

 private:
  // online safety checks, the analogue of StorageHandle::push_and_check
  // (tester.rs:379-397): committed-value agreement across peers + no gaps
  void push_and_check(int i, uint64_t index, uint64_t v) {
    for (int j = 0; j < n_; j++) {
      if (j != i && storage_[j].size() >= index &&
          storage_[j][index - 1] != v) {
        std::fprintf(stderr,
                     "commit mismatch at index %llu: node %d has %llu, node %d "
                     "has %llu\n",
                     (unsigned long long)index, i,
                     (unsigned long long)v, j,
                     (unsigned long long)storage_[j][index - 1]);
        std::abort();
      }
    }
    if (index == storage_[i].size() + 1) {
      storage_[i].push_back(v);
    } else if (index <= storage_[i].size()) {
      // re-apply after restart: must match what was applied before
      if (storage_[i][index - 1] != v) {
        std::fprintf(stderr, "node %d re-applied different value at %llu\n", i,
                     (unsigned long long)index);
        std::abort();
      }
    } else {
      std::fprintf(stderr, "node %d applied out of order: index %llu, have %zu\n",
                   i, (unsigned long long)index, storage_[i].size());
      std::abort();
    }
  }

  static Task<void> applier_task(RaftTester* t, int i, Channel<ApplyMsg> ch) {
    // runs as node i (killed on crash1); mirrors tester.rs:301-326
    for (;;) {
      auto m = co_await ch.recv();
      if (!m) break;
      if (m->is_snapshot) {
        if (t->rafts_[i] &&
            t->rafts_[i]->cond_install_snapshot(m->term, m->index, m->data)) {
          // snapshot payload = encoded applied-value prefix
          Dec d(m->data);
          uint64_t len = d.u64();
          t->storage_[i].clear();
          for (uint64_t k = 0; k < len; k++) t->storage_[i].push_back(d.u64());
        }
      } else {
        t->push_and_check(i, m->index, dec_u64(m->data));
        if (t->snapshot_ && m->index % SNAPSHOT_INTERVAL == 0 && t->rafts_[i]) {
          Enc e;
          e.u64(m->index);
          for (uint64_t k = 0; k < m->index; k++) e.u64(t->storage_[i][k]);
          t->rafts_[i]->snapshot(m->index, std::move(e.out));
        }
      }
    }
  }

  Sim* sim_;
  int n_;
  bool snapshot_;
  uint64_t start_time_;
  std::vector<Addr> addrs_;
  std::vector<std::shared_ptr<Raft>> rafts_;
  std::vector<bool> connected_;
  std::vector<std::vector<uint64_t>> storage_;  // applied values, 1-based index
};

}  // namespace raftcore
