// Lab 4A (shard controller) suite — the 2 mega-tests of the reference spec
// (SURVEY.md §4.3, /root/reference/src/shard_ctrler/tests.rs) re-expressed
// against the shard_ctrler layer on simcore. The minimal-transfer phases
// assert over the actual surviving gids (the reference's loop over 1..=npara
// is vacuous there since its gids are 100-series/1000-series).
//
// NOTE: no braced-init-list may appear in a statement containing co_await —
// gcc 12 cannot copy an initializer_list backing array into the coroutine
// frame ("array used as initializer"). The variadic builders below keep the
// braces out of co_await statements.
#include <cstdio>

#include "../shard_ctrler/ctrler_tester.h"
#include "framework.h"

using namespace shard_ctrler;
using simcore::Sim;
using simcore::SEC;

namespace {

using GroupMap = std::map<Gid, std::vector<Addr>>;

template <class... T>
std::vector<Addr> srvs(T... xs) {
  return {make_addr(0, 0, 0, unsigned(xs))...};
}
template <class... T>
std::vector<Gid> gidv(T... xs) {
  return {Gid(xs)...};
}
GroupMap grp(Gid g, std::vector<Addr> a) {
  GroupMap m;
  m.emplace(g, std::move(a));
  return m;
}

// old groups must not gain (join phase) / lose (leave phase) shards
void assert_minimal(const Config& before, const Config& after,
                    const std::vector<Gid>& old_gids, const char* what) {
  for (Gid g : old_gids) {
    for (size_t j = 0; j < N_SHARDS; j++) {
      if (after.shards[j] == g && before.shards[j] != g) {
        std::fprintf(stderr, "non-minimal transfer after %s (gid %llu)\n",
                     what, (unsigned long long)g);
        std::abort();
      }
    }
  }
}

// tests.rs:104-120
Task<void> basic_concurrent_client(CtrlerClerk ck, Gid gid) {
  co_await ck.join(grp(gid + 1000, srvs(gid + 1)));
  co_await ck.join(grp(gid, srvs(gid + 2)));
  co_await ck.leave(gidv(gid + 1000));
}

Task<void> basic_main(Sim* sim) {
  constexpr int NSERVERS = 3;
  CtrlerTester t(sim, NSERVERS, false);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();

  // Basic leave/join (tests.rs:29-62)
  std::vector<Config> cfa;
  cfa.push_back(co_await ck.query());
  co_await CtrlerTester::check(ck, gidv());

  auto addr1 = srvs(11, 12, 13);
  co_await ck.join(grp(1, addr1));
  co_await CtrlerTester::check(ck, gidv(1));
  cfa.push_back(co_await ck.query());

  auto addr2 = srvs(21, 22, 23);
  co_await ck.join(grp(2, addr2));
  co_await CtrlerTester::check(ck, gidv(1, 2));
  cfa.push_back(co_await ck.query());

  {
    Config cfx = co_await ck.query();
    MT_ASSERT(cfx.groups[1] == addr1);
    MT_ASSERT(cfx.groups[2] == addr2);
  }

  co_await ck.leave(gidv(1));
  co_await CtrlerTester::check(ck, gidv(2));
  cfa.push_back(co_await ck.query());

  co_await ck.leave(gidv(2));
  cfa.push_back(co_await ck.query());

  // Historical queries across rolling restarts (tests.rs:64-75)
  for (int s = 0; s < NSERVERS; s++) {
    t.shutdown_server(s);
    for (auto& cf : cfa) {
      Config c = co_await ck.query_at(cf.num);
      MT_ASSERT(c == cf);
    }
    co_await sim->spawn(t.start_server(s));
  }

  // Move (tests.rs:77-102)
  co_await ck.join(grp(503, srvs(31, 32, 33)));
  co_await ck.join(grp(504, srvs(41, 42, 43)));
  for (size_t i = 0; i < N_SHARDS; i++) {
    Config cf = co_await ck.query();
    Gid shard_gid = i < N_SHARDS / 2 ? 503 : 504;
    co_await ck.move_(i, shard_gid);
    if (cf.shards[i] != shard_gid) {
      Config cf1 = co_await ck.query();
      MT_ASSERT(cf1.num > cf.num);  // Move must advance the config number
    }
  }
  {
    Config cf2 = co_await ck.query();
    for (size_t i = 0; i < N_SHARDS; i++)
      MT_ASSERT_EQ(cf2.shards[i], (i < N_SHARDS / 2 ? 503u : 504u));
  }
  // Move rejection is SURFACED, not success-shaped (round-2 advisory): a
  // move to a never-joined gid reports rejected, changes no config, and a
  // valid move reports applied.
  {
    Config before = co_await ck.query();
    bool ok = co_await ck.move_(0, 999);  // gid 999 never joined
    MT_ASSERT(!ok);
    Config after = co_await ck.query();
    MT_ASSERT_EQ(after.num, before.num);
    MT_ASSERT(after == before);
    bool ok2 = co_await ck.move_(0, 504);
    MT_ASSERT(ok2);
    Config after2 = co_await ck.query();
    MT_ASSERT_EQ(after2.shards[0], 504u);
  }
  co_await ck.move_(0, 503);  // restore for the checks below
  co_await ck.leave(gidv(503));
  co_await ck.leave(gidv(504));

  // Concurrent leave/join (tests.rs:104-120)
  constexpr uint64_t NPARA = 10;
  std::vector<Gid> gids;
  for (uint64_t i = 0; i < NPARA; i++) gids.push_back(i * 10 + 100);
  {
    std::vector<simcore::TaskRef<void>> hs;
    for (Gid gid : gids)
      hs.push_back(sim->spawn(basic_concurrent_client(t.make_client(), gid)));
    for (auto& h : hs) co_await h;
  }
  co_await CtrlerTester::check(ck, gids);

  // Minimal transfers after joins (tests.rs:122-143)
  Config c1 = co_await ck.query();
  for (uint64_t i = 0; i < 5; i++) {
    Gid gid = NPARA + 1 + i;
    // duplicate gid+2 mirrors the reference fixture (tests.rs:128)
    co_await ck.join(grp(gid, srvs(gid + 1, gid + 2, gid + 2)));
  }
  Config c2 = co_await ck.query();
  assert_minimal(c1, c2, gids, "Join()s");

  // Minimal transfers after leaves (tests.rs:145-163)
  for (uint64_t i = 0; i < 5; i++) co_await ck.leave(gidv(NPARA + 1 + i));
  Config c3 = co_await ck.query();
  for (Gid g : gids) {
    for (size_t j = 0; j < N_SHARDS; j++)
      MT_ASSERT(!(c2.shards[j] == g && c3.shards[j] != g));
  }
  t.end();
}

// tests.rs:216-237
Task<void> multi_concurrent_client(CtrlerClerk ck, Gid gid) {
  GroupMap m = grp(gid, srvs(gid + 1, gid + 2, gid + 3));
  m.emplace(gid + 1000, srvs(gid + 1000 + 1));
  m.emplace(gid + 2000, srvs(gid + 2000 + 1));
  co_await ck.join(std::move(m));
  co_await ck.leave(gidv(gid + 1000, gid + 2000));
}

Task<void> multi_main(Sim* sim) {
  constexpr int NSERVERS = 3;
  CtrlerTester t(sim, NSERVERS, false);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();

  // Multi-group leave/join (tests.rs:175-214)
  co_await CtrlerTester::check(ck, gidv());
  auto addr1 = srvs(11, 12, 13);
  auto addr2 = srvs(21, 22, 23);
  {
    GroupMap m = grp(1, addr1);
    m.emplace(2, addr2);
    co_await ck.join(std::move(m));
  }
  co_await CtrlerTester::check(ck, gidv(1, 2));

  auto addr3 = srvs(31, 32, 33);
  co_await ck.join(grp(3, addr3));
  co_await CtrlerTester::check(ck, gidv(1, 2, 3));

  {
    Config cfx = co_await ck.query();
    MT_ASSERT(cfx.groups[1] == addr1);
    MT_ASSERT(cfx.groups[2] == addr2);
    MT_ASSERT(cfx.groups[3] == addr3);
  }

  co_await ck.leave(gidv(1, 3));
  co_await CtrlerTester::check(ck, gidv(2));
  {
    Config cfx = co_await ck.query();
    MT_ASSERT(cfx.groups[2] == addr2);
  }
  co_await ck.leave(gidv(2));

  // Concurrent multi leave/join (tests.rs:216-237)
  constexpr uint64_t NPARA = 10;
  std::vector<Gid> gids;
  for (uint64_t i = 0; i < NPARA; i++) gids.push_back(1000 + i);
  {
    std::vector<simcore::TaskRef<void>> hs;
    for (Gid gid : gids)
      hs.push_back(sim->spawn(multi_concurrent_client(t.make_client(), gid)));
    for (auto& h : hs) co_await h;
  }
  co_await CtrlerTester::check(ck, gids);

  // Minimal transfers after multijoins (tests.rs:239-257)
  Config c1 = co_await ck.query();
  {
    GroupMap m;
    for (uint64_t i = 0; i < 5; i++) {
      Gid gid = NPARA + 1 + i;
      m.emplace(gid, srvs(gid + 1, gid + 2));
    }
    co_await ck.join(std::move(m));
  }
  Config c2 = co_await ck.query();
  assert_minimal(c1, c2, gids, "multijoin");

  // Minimal transfers after multileaves (tests.rs:259-278)
  {
    std::vector<Gid> l;
    for (uint64_t i = 0; i < 5; i++) l.push_back(NPARA + 1 + i);
    co_await ck.leave(std::move(l));
  }
  Config c3 = co_await ck.query();
  for (Gid g : gids) {
    for (size_t j = 0; j < N_SHARDS; j++)
      MT_ASSERT(!(c2.shards[j] == g && c3.shards[j] != g));
  }

  // Same config on servers across leader kill (tests.rs:280-296)
  {
    auto leader = t.leader();
    MT_ASSERT(leader.has_value());
    Config c = co_await ck.query();
    t.shutdown_server(*leader);
    int attempts = 0;
    while (!t.leader().has_value()) {  // wait for re-election
      attempts++;
      MT_ASSERT(attempts < 10);
      co_await sim->sleep(1 * SEC);
    }
    Config cc = co_await ck.query();
    MT_ASSERT(c == cc);
  }
  t.end();
}

}  // namespace

MT_TEST(ctrler_basic_4a) {
  Sim sim(seed);
  MT_ASSERT(sim.run(basic_main(&sim)));
}
MT_TEST(ctrler_multi_4a) {
  Sim sim(seed);
  MT_ASSERT(sim.run(multi_main(&sim)));
}

// ---- config_read_4a: the raft-free ConfigRead fan-out (seed-7036 regression,
// PERF.md round 5). The 4B config poller learns configs through this path, so
// it must (a) be answered replica-locally by ANY server — including followers
// and a minority partition's members — for a config that replica has applied,
// (b) cost exactly one request + one reply, never a raft commit, and (c)
// answer ok=false (not a stale config) for a num the replica hasn't applied.
namespace {
Task<void> config_read_main(Sim* sim) {
  CtrlerTester t(sim, 3, false);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await sim->spawn(ck.join(grp(1, srvs(1, 2, 3))));
  co_await sim->sleep(1 * SEC);  // let followers apply config 1

  Addr probe = make_addr(0, 0, 9, 9);
  for (int i = 0; i < 3; i++) {
    Addr a = make_addr(0, 0, 1, i + 1);
    auto rep = co_await sim->spawn(
        probe, [](Sim* s, Addr dst) -> Task<std::optional<ConfigRead::Reply>> {
          co_return co_await s->call_timeout(dst, ConfigRead{1}, 500 * MSEC);
        }(sim, a));
    MT_ASSERT(rep.has_value() && rep->ok);
    raftcore::Dec d(rep->data);
    Config c = Config::dec(d);
    MT_ASSERT_EQ(c.num, 1u);
    MT_ASSERT(c.groups.count(1));

    auto future = co_await sim->spawn(
        probe, [](Sim* s, Addr dst) -> Task<std::optional<ConfigRead::Reply>> {
          co_return co_await s->call_timeout(dst, ConfigRead{7}, 500 * MSEC);
        }(sim, a));
    MT_ASSERT(future.has_value() && !future->ok);  // unapplied num: miss
  }

  // Replica-locality proof: with the majority dead no consensus op can
  // commit, yet the survivor still answers ConfigRead from applied state —
  // exactly what keeps a 4B group learning configs through ctrler churn.
  t.shutdown_server(1);
  t.shutdown_server(2);
  auto lone = co_await sim->spawn(
      probe, [](Sim* s, Addr dst) -> Task<std::optional<ConfigRead::Reply>> {
        co_return co_await s->call_timeout(dst, ConfigRead{1}, 500 * MSEC);
      }(sim, make_addr(0, 0, 1, 1)));
  MT_ASSERT(lone.has_value() && lone->ok);
  t.end();
}
}  // namespace

MT_TEST(ctrler_config_read_4a) {
  Sim sim(seed);
  MT_ASSERT(sim.run(config_read_main(&sim)));
}
