// sim-core unit tests: virtual time, tasks, RPC, faults, fs, determinism.
// These validate the §2.6 simulator contract before any Raft runs on it.
#include "../simcore/simcore.h"
#include "framework.h"

using namespace simcore;

static constexpr Addr A = make_addr(0, 0, 1, 1);
static constexpr Addr B = make_addr(0, 0, 1, 2);
static constexpr Addr C = make_addr(0, 0, 1, 3);

// ---- virtual time: sleeps cost nothing real, order by duration
MT_TEST(sim_virtual_time) {
  Sim sim(seed);
  auto body = [](Sim* s, std::vector<int>* order) -> Task<void> {
    auto t1 = s->spawn(A, [](Sim* s, std::vector<int>* o) -> Task<void> {
      co_await s->sleep(20 * MSEC);
      o->push_back(2);
    }(s, order));
    auto t2 = s->spawn(B, [](Sim* s, std::vector<int>* o) -> Task<void> {
      co_await s->sleep(10 * MSEC);
      o->push_back(1);
    }(s, order));
    co_await t1;
    co_await t2;
    MT_ASSERT_EQ(s->now(), 20 * MSEC);
  };
  std::vector<int> order;
  MT_ASSERT(sim.run(body(&sim, &order)));
  MT_ASSERT_EQ(order.size(), 2u);
  MT_ASSERT_EQ(order[0], 1);
  MT_ASSERT_EQ(order[1], 2);
}

// ---- typed RPC roundtrip + msg_count (request + reply = 2)
struct Echo {
  int x;
  using Reply = int;
};

static Task<void> serve_echo(Sim* s) {
  s->add_rpc_handler<Echo>([](Echo e) -> Task<int> { co_return e.x * 2; });
  co_return;
}

MT_TEST(sim_rpc_roundtrip) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    co_await s->spawn(B, serve_echo(s));
    auto r = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{21}, 500 * MSEC);
      MT_ASSERT(v.has_value());
      co_return *v;
    }(s));
    MT_ASSERT_EQ(r, 42);
    MT_ASSERT_EQ(s->msg_count(), 2u);
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- disconnect => timeout at exactly the deadline; reconnect heals
MT_TEST(sim_disconnect_timeout) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    co_await s->spawn(B, serve_echo(s));
    s->disconnect(B);
    uint64_t t0 = s->now();
    auto r = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{1}, 500 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    MT_ASSERT_EQ(r, -1);
    MT_ASSERT_EQ(s->now() - t0, 500 * MSEC);
    s->connect(B);
    auto r2 = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{2}, 500 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    MT_ASSERT_EQ(r2, 4);
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- pairwise partition: A-B blocked, A-C fine (connect2/disconnect2)
MT_TEST(sim_pairwise_partition) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    co_await s->spawn(B, serve_echo(s));
    co_await s->spawn(C, serve_echo(s));
    s->disconnect2(A, B);
    auto rb = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{1}, 100 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    auto rc = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(C, Echo{3}, 100 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    MT_ASSERT_EQ(rb, -1);
    MT_ASSERT_EQ(rc, 6);
    s->connect2(A, B);
    auto rb2 = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{5}, 100 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    MT_ASSERT_EQ(rb2, 10);
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- kill: tasks die, handlers vanish (calls time out), fs survives
MT_TEST(sim_kill_and_fs) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    co_await s->spawn(B, [](Sim* s) -> Task<void> {
      s->fs_write("state", Bytes{1, 2, 3});
      s->add_rpc_handler<Echo>([](Echo e) -> Task<int> { co_return e.x; });
      co_return;
    }(s));
    // ticker task on B that must stop at kill
    auto counter = std::make_shared<int>(0);
    s->spawn(B, [](Sim* s, std::shared_ptr<int> c) -> Task<void> {
      for (;;) {
        co_await s->sleep(10 * MSEC);
        (*c)++;
      }
    }(s, counter));
    co_await s->sleep(105 * MSEC);
    int before = *counter;
    MT_ASSERT(before >= 9);
    s->kill(B);
    co_await s->sleep(100 * MSEC);
    MT_ASSERT_EQ(*counter, before);  // ticker died with the node
    auto r = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{7}, 100 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    MT_ASSERT_EQ(r, -1);  // handler gone
    MT_ASSERT_EQ(s->fs_size(B, "state"), 3u);  // disk survived the crash
    // "restart": node code reads its persisted file
    auto got = co_await s->spawn(B, [](Sim* s) -> Task<int> {
      auto data = s->fs_read("state");
      co_return data ? (int)data->size() : -1;
    }(s));
    MT_ASSERT_EQ(got, 3);
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- channel: single-consumer apply-stream semantics
MT_TEST(sim_channel) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    Channel<int> ch;
    auto consumer = s->spawn(A, [](Sim* s, Channel<int> ch,
                                   std::shared_ptr<std::vector<int>> got)
                                    -> Task<void> {
      for (;;) {
        auto v = co_await ch.recv();
        if (!v) break;
        got->push_back(*v);
      }
    }(s, ch, std::make_shared<std::vector<int>>()));
    s->spawn(B, [](Sim* s, Channel<int> ch) -> Task<void> {
      for (int i = 0; i < 5; i++) {
        co_await s->sleep(1 * MSEC);
        ch.send(i);
      }
      ch.close();
    }(s, ch));
    co_await consumer;
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- abort: a dropped client task stops executing (shardkv tests drop
// clients mid-flight, tests.rs:55)
MT_TEST(sim_abort_task) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    auto counter = std::make_shared<int>(0);
    auto t = s->spawn(A, [](Sim* s, std::shared_ptr<int> c) -> Task<void> {
      for (;;) {
        co_await s->sleep(5 * MSEC);
        (*c)++;
      }
    }(s, counter));
    co_await s->sleep(26 * MSEC);
    t.abort();
    int at_abort = *counter;
    co_await s->sleep(50 * MSEC);
    MT_ASSERT_EQ(*counter, at_abort);
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- full-loss network: every call times out
MT_TEST(sim_full_loss) {
  Sim sim(seed);
  sim.net_config().packet_loss_rate = 1.0;
  auto body = [](Sim* s) -> Task<void> {
    co_await s->spawn(B, serve_echo(s));
    auto r = co_await s->spawn(A, [](Sim* s) -> Task<int> {
      auto v = co_await s->call_timeout(B, Echo{1}, 50 * MSEC);
      co_return v.has_value() ? *v : -1;
    }(s));
    MT_ASSERT_EQ(r, -1);
    MT_ASSERT_EQ(s->msg_count(), 0u);
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- determinism: identical seeds => identical trace hash & msg_count,
// different seeds diverge (lossy net exercises the RNG heavily)
static uint64_t noisy_scenario(uint64_t seed, uint64_t* msgs) {
  Sim sim(seed);
  sim.net_config().packet_loss_rate = 0.3;
  sim.net_config().send_latency_min = 1 * MSEC;
  sim.net_config().send_latency_max = 27 * MSEC;
  auto body = [](Sim* s) -> Task<void> {
    co_await s->spawn(B, serve_echo(s));
    for (int i = 0; i < 50; i++) {
      auto v = co_await s->spawn(A, [](Sim* s, int i) -> Task<int> {
        auto r = co_await s->call_timeout(B, Echo{i}, 40 * MSEC);
        co_return r.has_value() ? *r : -1;
      }(s, i));
      (void)v;
    }
  };
  MT_ASSERT(sim.run(body(&sim)));
  *msgs = sim.msg_count();
  return sim.trace_hash();
}

MT_TEST(sim_determinism) {
  uint64_t m1, m2, m3;
  uint64_t h1 = noisy_scenario(seed, &m1);
  uint64_t h2 = noisy_scenario(seed, &m2);
  uint64_t h3 = noisy_scenario(seed + 1, &m3);
  MT_ASSERT_EQ(h1, h2);
  MT_ASSERT_EQ(m1, m2);
  MT_ASSERT(h1 != h3);
}

// ---- watchdog self-test: NOT in the default suite (main.cpp skips
// "wdog_selftest_*" unless named explicitly). A clerk-shaped retry loop that
// burns virtual time forever — the seed-7036 hang shape. Run it with a small
// MADTPU_TEST_VIRT_CAP and the watchdog must abort naming this test and both
// clocks; tests/test_cpp_suite.py asserts exactly that.
MT_TEST(wdog_selftest_wedge) {
  Sim sim(seed);
  auto body = [](Sim* s) -> Task<void> {
    for (;;) co_await s->sleep(100 * MSEC);  // virtual progress, no real work
  };
  MT_ASSERT(sim.run(body(&sim)));
}

// ---- SIGALRM backstop self-test: a CPU-bound spin that never returns to
// the event loop, so the in-sim watchdog cannot fire — only the runner's
// alarm can. Excluded from run-all like the wedge above.
MT_TEST(wdog_selftest_spin) {
  Sim sim(seed);
  auto body = [](Sim*) -> Task<void> {
    for (volatile uint64_t i = 0;; i++) {
    }  // never yields
    co_return;
  };
  MT_ASSERT(sim.run(body(&sim)));
}
