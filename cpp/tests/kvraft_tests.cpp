// Lab 3 (fault-tolerant KV on Raft) suite — the 19 active tests of the
// reference spec (SURVEY.md §4.2, /root/reference/src/kvraft/tests.rs)
// re-expressed against the kvraft layer on simcore. Each test is a function
// of the seed; failures replay with MADTPU_TEST_SEED=<n>.
#include <cstdio>
#include <memory>
#include <string>

#include "../kvraft/kv_tester.h"
#include "../kvraft/linearize.h"
#include "framework.h"

using namespace kvraft;
using simcore::Sim;

namespace {

// tests.rs:21-43 — every append by `clnt` present exactly once, in order
void check_clnt_appends(int clnt, const std::string& v, uint64_t count) {
  std::optional<size_t> lastoff;
  for (uint64_t j = 0; j < count; j++) {
    char buf[64];
    std::snprintf(buf, sizeof buf, "x %d %llu y", clnt, (unsigned long long)j);
    std::string wanted = buf;
    size_t off = v.find(wanted);
    if (off == std::string::npos) {
      std::fprintf(stderr, "client %d missing element %s in Append result\n",
                   clnt, wanted.c_str());
      std::abort();
    }
    size_t off1 = v.rfind(wanted);
    if (off1 != off) {
      std::fprintf(stderr, "duplicate element %s in Append result\n",
                   wanted.c_str());
      std::abort();
    }
    if (lastoff && off <= *lastoff) {
      std::fprintf(stderr, "wrong order for element %s in Append result\n",
                   wanted.c_str());
      std::abort();
    }
    lastoff = off;
  }
}

void check_concurrent_appends(const std::string& v,
                              const std::vector<uint64_t>& counts) {
  for (size_t i = 0; i < counts.size(); i++)
    check_clnt_appends((int)i, v, counts[i]);
}

// tests.rs:107-131 — append/get loop predicting the value client-side
simcore::Task<uint64_t> generic_client(Sim* sim, KvTester::Clerk ck, int cli,
                                       std::shared_ptr<bool> done) {
  uint64_t j = 0;
  std::string last;
  std::string key = std::to_string(cli);
  co_await ck.put(key, last);
  while (!*done) {
    if (sim->rand_bool(0.5)) {
      char buf[64];
      std::snprintf(buf, sizeof buf, "x %d %llu y", cli, (unsigned long long)j);
      last += buf;
      co_await ck.append(key, buf);
      j++;
    } else {
      std::string v = co_await ck.get(key);
      if (v != last) {
        std::fprintf(stderr, "client %d got wrong value for key %s\n", cli,
                     key.c_str());
        std::abort();
      }
    }
  }
  co_return j;
}

// tests.rs:134-157 — concurrent random repartitioner
simcore::Task<void> repartitioner(Sim* sim, KvTester* t,
                                  std::shared_ptr<bool> done) {
  auto all = t->all();
  int n = (int)all.size();
  while (!*done) {
    for (int i = n - 1; i > 0; i--)
      std::swap(all[i], all[(int)(sim->rand_u64() % uint64_t(i + 1))]);
    int k = (int)(sim->rand_u64() % uint64_t(n));
    std::vector<int> left(all.begin(), all.begin() + k);
    std::vector<int> right(all.begin() + k, all.end());
    t->partition(left, right);
    co_await sim->sleep(KV_ELECTION_TIMEOUT + sim->rand_range(0, 200) * MSEC);
  }
}

// tests.rs:65-220
simcore::Task<void> generic_main(Sim* sim, int nclients, bool unreliable,
                                 bool crash, bool partitions,
                                 std::optional<size_t> maxraftstate) {
  constexpr int NSERVERS = 5;
  KvTester t(sim, NSERVERS, unreliable, maxraftstate);
  co_await sim->spawn(t.init());
  auto ck = t.make_client(t.all());

  for (int iter = 0; iter < 3; iter++) {
    auto done = std::make_shared<bool>(false);
    std::vector<simcore::TaskRef<uint64_t>> cas;
    for (int cli = 0; cli < nclients; cli++)
      cas.push_back(sim->spawn(
          generic_client(sim, t.make_client(t.all()), cli, done)));

    simcore::TaskRef<void> parter;
    if (partitions) {
      // let the clients run uninterrupted for a while first
      co_await sim->sleep(1 * SEC);
      parter = sim->spawn(repartitioner(sim, &t, done));
    }
    co_await sim->sleep(5 * SEC);
    *done = true;

    if (partitions) {
      co_await parter;
      // a client may be stuck on a minority server until a new term starts
      t.connect_all();
      co_await sim->sleep(KV_ELECTION_TIMEOUT);
    }
    if (crash) {
      for (int i = 0; i < NSERVERS; i++) t.shutdown_server(i);
      co_await sim->sleep(KV_ELECTION_TIMEOUT);
      for (int i = 0; i < NSERVERS; i++) co_await sim->spawn(t.start_server(i));
      t.connect_all();
    }

    for (int cli = 0; cli < nclients; cli++) {
      uint64_t j = co_await cas[cli];
      std::string v = co_await ck.get(std::to_string(cli));
      check_clnt_appends(cli, v, j);
    }

    if (maxraftstate) {
      if (t.log_size() > 2 * *maxraftstate) {
        std::fprintf(stderr, "logs were not trimmed (%zu > 2*%zu)\n",
                     t.log_size(), *maxraftstate);
        std::abort();
      }
    }
  }
  t.end();
}

void run_generic(uint64_t seed, int nclients, bool unreliable, bool crash,
                 bool partitions, std::optional<size_t> maxraftstate) {
  Sim sim(seed);
  MT_ASSERT(sim.run(generic_main(&sim, nclients, unreliable, crash, partitions,
                                 maxraftstate)));
}

#define TSLEEP(ns) co_await sim->sleep(ns)

}  // namespace

// ------------------------------------------------------------------ 3A

MT_TEST(kv_basic_3a) { run_generic(seed, 1, false, false, false, {}); }
MT_TEST(kv_concurrent_3a) { run_generic(seed, 5, false, false, false, {}); }
MT_TEST(kv_unreliable_3a) { run_generic(seed, 5, true, false, false, {}); }

namespace {
// tests.rs:241-274
simcore::Task<void> one_key_client(KvTester::Clerk ck, int i, uint64_t upto) {
  for (uint64_t n = 0; n < upto; n++) {
    char buf[64];
    std::snprintf(buf, sizeof buf, "x %d %llu y", i, (unsigned long long)n);
    co_await ck.append("k", buf);
  }
}

simcore::Task<void> unreliable_one_key_main(Sim* sim) {
  KvTester t(sim, 3, true, {});
  co_await sim->spawn(t.init());
  auto ck = t.make_client(t.all());
  co_await ck.put("k", "");

  constexpr int NCLIENT = 5;
  constexpr uint64_t UPTO = 10;
  std::vector<simcore::TaskRef<void>> cas;
  for (int i = 0; i < NCLIENT; i++)
    cas.push_back(sim->spawn(one_key_client(t.make_client(t.all()), i, UPTO)));
  for (auto& c : cas) co_await c;

  std::string vx = co_await ck.get("k");
  check_concurrent_appends(vx, std::vector<uint64_t>(NCLIENT, UPTO));
  t.end();
}
}  // namespace

MT_TEST(kv_unreliable_one_key_3a) {
  Sim sim(seed);
  MT_ASSERT(sim.run(unreliable_one_key_main(&sim)));
}

namespace {
// tests.rs:277-339 — no progress in a minority partition until heal
simcore::Task<void> one_partition_main(Sim* sim) {
  KvTester t(sim, 5, false, {});
  co_await sim->spawn(t.init());
  auto all = t.all();
  auto ck = t.make_client(all);
  co_await ck.put("1", "13");

  auto [p1, p2] = t.make_partition();
  t.partition(p1, p2);

  auto ckp1 = t.make_client(p1);    // majority
  auto ckp2a = t.make_client(p2);   // minority (has the old leader)
  auto ckp2b = t.make_client(p2);

  co_await ckp1.put("1", "14");
  co_await ckp1.check("1", "14");

  // no progress in minority
  auto put = sim->spawn(ckp2a.put("1", "15"));
  auto get = sim->spawn(ckp2b.get("1"));
  TSLEEP(1 * SEC);
  MT_ASSERT(!put.done());  // put in minority must not complete
  MT_ASSERT(!get.done());  // get in minority must not complete

  co_await ckp1.check("1", "14");
  co_await ckp1.put("1", "16");
  co_await ckp1.check("1", "16");

  // completion after heal
  t.connect_all();
  t.connect_client(ckp2a.id(), all);
  t.connect_client(ckp2b.id(), all);
  TSLEEP(KV_ELECTION_TIMEOUT);

  uint64_t t0 = sim->now();
  while ((!put.done() || !get.done()) && sim->now() - t0 < 3 * SEC)
    TSLEEP(20 * MSEC);
  MT_ASSERT(put.done());  // put must complete after heal
  MT_ASSERT(get.done());  // get must complete after heal

  co_await ck.check("1", "15");
  t.end();
}
}  // namespace

MT_TEST(kv_one_partition_3a) {
  Sim sim(seed);
  MT_ASSERT(sim.run(one_partition_main(&sim)));
}

MT_TEST(kv_many_partitions_one_client_3a) {
  run_generic(seed, 1, false, false, true, {});
}
MT_TEST(kv_many_partitions_many_clients_3a) {
  run_generic(seed, 5, false, false, true, {});
}
MT_TEST(kv_persist_one_client_3a) {
  run_generic(seed, 1, false, true, false, {});
}
MT_TEST(kv_persist_concurrent_3a) {
  run_generic(seed, 5, false, true, false, {});
}
MT_TEST(kv_persist_concurrent_unreliable_3a) {
  run_generic(seed, 5, true, true, false, {});
}
MT_TEST(kv_persist_partition_3a) {
  run_generic(seed, 5, false, true, true, {});
}
MT_TEST(kv_persist_partition_unreliable_3a) {
  run_generic(seed, 5, true, true, true, {});
}

// ------------------------------------------------------------------ 3B

namespace {
// tests.rs:397-455 — lagging node catches up via InstallSnapshot; majority
// discards committed entries even when a minority doesn't respond
simcore::Task<void> snapshot_rpc_main(Sim* sim) {
  constexpr size_t MAXRAFTSTATE = 1000;
  KvTester t(sim, 3, false, MAXRAFTSTATE);
  co_await sim->spawn(t.init());
  auto all = t.all();
  auto ck = t.make_client(all);

  co_await ck.put("a", "A");
  co_await ck.check("a", "A");

  // a bunch of puts into the majority partition
  t.partition({0, 1}, {2});
  {
    auto ck1 = t.make_client({0, 1});
    for (int i = 0; i < 50; i++) {
      auto s = std::to_string(i);
      co_await ck1.put(s, s);
    }
    TSLEEP(KV_ELECTION_TIMEOUT);
    co_await ck1.put("b", "B");
  }
  MT_ASSERT(t.log_size() <= 2 * MAXRAFTSTATE);  // logs must be trimmed

  // now a group that needs the lagging server, so it must catch up
  t.partition({0, 2}, {1});
  {
    auto ck1 = t.make_client({0, 2});
    co_await ck1.put("c", "C");
    co_await ck1.put("d", "D");
    co_await ck1.check("a", "A");
    co_await ck1.check("b", "B");
    co_await ck1.check("1", "1");
    co_await ck1.check("49", "49");
  }

  t.partition({0, 1, 2}, {});
  co_await ck.put("e", "E");
  co_await ck.check("c", "C");
  co_await ck.check("e", "E");
  co_await ck.check("1", "1");
  t.end();
}

// tests.rs:459-493 — snapshots must stay small
simcore::Task<void> snapshot_size_main(Sim* sim) {
  constexpr size_t MAXRAFTSTATE = 1000;
  constexpr size_t MAXSNAPSHOT = 500;
  KvTester t(sim, 3, false, MAXRAFTSTATE);
  co_await sim->spawn(t.init());
  auto ck = t.make_client(t.all());

  for (int i = 0; i < 200; i++) {
    co_await ck.put("x", "0");
    co_await ck.check("x", "0");
    co_await ck.put("x", "1");
    co_await ck.check("x", "1");
  }
  MT_ASSERT(t.log_size() <= 2 * MAXRAFTSTATE);
  MT_ASSERT(t.snapshot_size() <= MAXSNAPSHOT);
  t.end();
}
}  // namespace

MT_TEST(kv_snapshot_rpc_3b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(snapshot_rpc_main(&sim)));
}
MT_TEST(kv_snapshot_size_3b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(snapshot_size_main(&sim)));
}
MT_TEST(kv_snapshot_recover_3b) {
  run_generic(seed, 1, false, true, false, 1000);
}
MT_TEST(kv_snapshot_recover_many_clients_3b) {
  run_generic(seed, 20, false, true, false, 1000);
}
MT_TEST(kv_snapshot_unreliable_3b) {
  run_generic(seed, 5, true, false, false, 1000);
}
MT_TEST(kv_snapshot_unreliable_recover_3b) {
  run_generic(seed, 5, true, true, false, 1000);
}
MT_TEST(kv_snapshot_unreliable_recover_concurrent_partition_3b) {
  run_generic(seed, 5, true, true, true, 1000);
}

// ------------------------------------------- linearizability (tests.rs:386-390,
// 524-528 — commented out upstream; implemented here per SURVEY.md §4.2/§7)
namespace {

// checker self-validation: known-good and known-bad histories
void linearize_checker_unit(uint64_t) {
  using kvraft::HistOp;
  auto op = [](uint64_t inv, uint64_t ret, Op::Kind k, std::string key,
               std::string in, std::string out) {
    HistOp h;
    h.invoke = inv;
    h.ret = ret;
    h.kind = k;
    h.key = std::move(key);
    h.input = std::move(in);
    h.output = std::move(out);
    return h;
  };
  // sequential read-write-read: linearizable
  std::vector<HistOp> good{
      op(0, 5, Op::Kind::Get, "k", "", ""),
      op(6, 10, Op::Kind::Put, "k", "a", ""),
      op(11, 15, Op::Kind::Get, "k", "", "a"),
      op(16, 20, Op::Kind::Append, "k", "b", ""),
      op(21, 25, Op::Kind::Get, "k", "", "ab"),
  };
  MT_ASSERT(kvraft::check_linearizable_kv(good));
  // concurrent write overlap: reads may see either order, consistently
  std::vector<HistOp> good2{
      op(0, 10, Op::Kind::Put, "k", "a", ""),
      op(0, 10, Op::Kind::Put, "k", "b", ""),
      op(20, 30, Op::Kind::Get, "k", "", "b"),
      op(40, 50, Op::Kind::Get, "k", "", "b"),
  };
  MT_ASSERT(kvraft::check_linearizable_kv(good2));
  // stale read: a completed put must be visible to a later get
  std::vector<HistOp> stale{
      op(0, 10, Op::Kind::Put, "k", "a", ""),
      op(20, 30, Op::Kind::Get, "k", "", ""),
  };
  MT_ASSERT(!kvraft::check_linearizable_kv(stale));
  // flip-flop reads with no interleaving write: not linearizable
  std::vector<HistOp> flip{
      op(0, 10, Op::Kind::Put, "k", "a", ""),
      op(0, 10, Op::Kind::Put, "k", "b", ""),
      op(20, 30, Op::Kind::Get, "k", "", "a"),
      op(40, 50, Op::Kind::Get, "k", "", "b"),
  };
  MT_ASSERT(!kvraft::check_linearizable_kv(flip));
  // duplicate append visible: not linearizable
  std::vector<HistOp> dup{
      op(0, 10, Op::Kind::Append, "k", "x", ""),
      op(20, 30, Op::Kind::Get, "k", "", "xx"),
  };
  MT_ASSERT(!kvraft::check_linearizable_kv(dup));
  // per-key decomposition: independent keys don't constrain each other
  std::vector<HistOp> multi{
      op(0, 10, Op::Kind::Put, "a", "1", ""),
      op(0, 10, Op::Kind::Put, "b", "2", ""),
      op(20, 30, Op::Kind::Get, "a", "", "1"),
      op(20, 30, Op::Kind::Get, "b", "", "2"),
  };
  MT_ASSERT(kvraft::check_linearizable_kv(multi));
}

// a client doing random get/put/append on a small key set, recording the
// history with virtual invoke/return times
simcore::Task<void> lin_client(Sim* sim, KvTester::Clerk ck, int cli,
                               std::shared_ptr<bool> done,
                               std::shared_ptr<std::vector<kvraft::HistOp>> hist) {
  uint64_t j = 0;
  while (!*done) {
    kvraft::HistOp h;
    h.key = std::to_string((int)(sim->rand_u64() % 3));
    double r = sim->rand_f64();
    h.invoke = sim->now();
    if (r < 0.5) {
      h.kind = Op::Kind::Get;
      h.output = co_await ck.get(h.key);
    } else {
      char buf[64];
      std::snprintf(buf, sizeof buf, "v%d.%llu ", cli, (unsigned long long)j++);
      h.input = buf;
      if (r < 0.75) {
        h.kind = Op::Kind::Put;
        co_await ck.put(h.key, h.input);
      } else {
        h.kind = Op::Kind::Append;
        co_await ck.append(h.key, h.input);
      }
    }
    h.ret = sim->now();
    hist->push_back(std::move(h));
    co_await sim->sleep(sim->rand_range(5, 50) * MSEC);
  }
}

// generic_test_linearizability (tests.rs:389/527): concurrent clients under
// partitions + crashes (+ snapshots for 3B); full-history linearizability
// check instead of client-side value prediction
simcore::Task<void> lin_main(Sim* sim, int nclients, bool unreliable,
                             std::optional<size_t> maxraftstate) {
  constexpr int NSERVERS = 5;
  KvTester t(sim, NSERVERS, unreliable, maxraftstate);
  co_await sim->spawn(t.init());
  auto hist = std::make_shared<std::vector<kvraft::HistOp>>();

  for (int iter = 0; iter < 2; iter++) {
    auto done = std::make_shared<bool>(false);
    std::vector<simcore::TaskRef<void>> cas;
    for (int cli = 0; cli < nclients; cli++)
      cas.push_back(sim->spawn(
          lin_client(sim, t.make_client(t.all()), cli, done, hist)));

    co_await sim->sleep(1 * SEC);
    auto parter = sim->spawn(repartitioner(sim, &t, done));
    co_await sim->sleep(4 * SEC);
    *done = true;
    co_await parter;
    t.connect_all();
    co_await sim->sleep(KV_ELECTION_TIMEOUT);

    // crash-restart the whole cluster mid-history
    for (int i = 0; i < NSERVERS; i++) t.shutdown_server(i);
    co_await sim->sleep(KV_ELECTION_TIMEOUT);
    for (int i = 0; i < NSERVERS; i++) co_await sim->spawn(t.start_server(i));
    t.connect_all();

    for (auto& c : cas) co_await c;  // all ops complete: no open invocations
  }
  MT_ASSERT(kvraft::check_linearizable_kv(*hist));
  std::printf("  linearizability: %zu ops OK\n", hist->size());
  t.end();
}

}  // namespace

MT_TEST(kv_linearize_checker_unit) { linearize_checker_unit(seed); }
MT_TEST(kv_linearizability_3a) {
  Sim sim(seed);
  MT_ASSERT(sim.run(lin_main(&sim, 7, true, {})));
}
MT_TEST(kv_linearizability_3b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(lin_main(&sim, 7, true, 1000)));
}
