// Minimal deterministic test harness for the C++ suites.
//
// Mirrors the reference's test-runner semantics (README.md:42-87) the
// framework way: every test is a function of a seed; the runner prints the
// seed so any failure replays exactly with MADTPU_TEST_SEED=<n>; REPLAYS
// (MADTPU_TEST_NUM) rerun with fresh seeds; MADTPU_TEST_CHECK_DETERMINISTIC
// runs each test twice and compares the simulator trace hash.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <functional>
#include <string>
#include <vector>

namespace mtest {

struct TestCase {
  const char* name;
  void (*fn)(uint64_t seed);
};

inline std::vector<TestCase>& registry() {
  static std::vector<TestCase> r;
  return r;
}

struct Register {
  Register(const char* name, void (*fn)(uint64_t)) {
    registry().push_back({name, fn});
  }
};

#define MT_TEST(name)                                \
  static void name(uint64_t seed);                   \
  static ::mtest::Register _reg_##name(#name, name); \
  static void name(uint64_t seed)

#define MT_ASSERT(cond)                                                     \
  do {                                                                      \
    if (!(cond)) {                                                          \
      std::fprintf(stderr, "ASSERT FAILED %s:%d: %s\n", __FILE__, __LINE__, \
                   #cond);                                                  \
      std::abort();                                                         \
    }                                                                       \
  } while (0)

#define MT_ASSERT_EQ(a, b)                                                   \
  do {                                                                       \
    auto _a = (a);                                                           \
    auto _b = (b);                                                           \
    if (!(_a == _b)) {                                                       \
      std::fprintf(stderr, "ASSERT_EQ FAILED %s:%d: %s=%lld vs %s=%lld\n",   \
                   __FILE__, __LINE__, #a, (long long)_a, #b, (long long)_b); \
      std::abort();                                                          \
    }                                                                        \
  } while (0)

}  // namespace mtest
