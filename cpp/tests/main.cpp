// Test runner: ./madtpu_tests [--list | test_name ...]; no args = run all.
// Env (the reference's MADSIM_* contract, README.md:42-87):
//   MADTPU_TEST_SEED   — fixed seed for exact replay
//   MADTPU_TEST_NUM    — rerun each test N times with fresh seeds
//   MADTPU_TEST_CHECK_DETERMINISTIC=1 — run each test twice with the same
//     seed and compare the accumulated simulator trace hashes; any
//     schedule-dependent behavior fails loudly.
#include <chrono>
#include <csignal>
#include <cstring>
#include <unistd.h>

#include "../simcore/simcore.h"
#include "framework.h"

namespace {
uint64_t g_hash_acc = 0;
const char* g_current_test = "?";
unsigned g_alarm_s = 0;  // SIGALRM backstop budget (0 = disabled)

// The in-loop watchdog (Sim::run) can only fire between events; a CPU-bound
// or blocked handler never returns to it. SIGALRM is the backstop for that
// class: it interrupts anything and still names the test. Handler is
// async-signal-safe (write + _exit only).
extern "C" void wdog_alarm_handler(int) {
  auto put = [](const char* s) {
    ssize_t r = write(2, s, std::strlen(s));
    (void)r;
  };
  put("[WDOG ] test ");
  put(g_current_test);
  put(" hit the SIGALRM real-time backstop (CPU-bound or blocked hang)\n");
  _exit(124);
}

void run_once(const mtest::TestCase& t, uint64_t s) {
  std::printf("[ RUN  ] %s  MADTPU_TEST_SEED=%llu\n", t.name,
              (unsigned long long)s);
  std::fflush(stdout);
  g_current_test = t.name;
  if (g_alarm_s) alarm(g_alarm_s);
  t.fn(s);
  if (g_alarm_s) alarm(0);
  std::printf("[ OK   ] %s\n", t.name);
  std::fflush(stdout);
}
}  // namespace

int main(int argc, char** argv) {
  auto& tests = mtest::registry();
  if (argc > 1 && std::strcmp(argv[1], "--list") == 0) {
    for (auto& t : tests) std::printf("%s\n", t.name);
    return 0;
  }
  uint64_t seed;
  const char* env_seed = std::getenv("MADTPU_TEST_SEED");
  if (env_seed)
    seed = std::strtoull(env_seed, nullptr, 10);
  else
    seed = (uint64_t)std::chrono::steady_clock::now().time_since_epoch().count();
  int reruns = 1;
  if (const char* n = std::getenv("MADTPU_TEST_NUM")) reruns = std::atoi(n);
  // Per-test liveness watchdog (reference tester.rs:353-358 — 120 s panic),
  // plus a virtual-time cap for livelocks that keep virtual time moving.
  // MADTPU_TEST_REAL_CAP / MADTPU_TEST_VIRT_CAP (seconds, 0 disables) tune it.
  auto& wd = simcore::Sim::watchdog();
  wd.enabled = true;
  wd.name_fn = [] { return g_current_test; };
  if (const char* c = std::getenv("MADTPU_TEST_REAL_CAP"))
    wd.real_cap_s = std::atof(c);
  if (const char* c = std::getenv("MADTPU_TEST_VIRT_CAP"))
    wd.virt_cap_s = std::atof(c);
  if (wd.real_cap_s > 0) {
    std::signal(SIGALRM, wdog_alarm_handler);
    // slack so the in-loop check (with virt detail) fires first when it can
    g_alarm_s = unsigned(wd.real_cap_s + wd.real_cap_s / 8 + 2);
  }
  const char* det_env = std::getenv("MADTPU_TEST_CHECK_DETERMINISTIC");
  bool check_det = det_env && det_env[0] && det_env[0] != '0';
  if (check_det)
    simcore::Sim::trace_observer() = [](uint64_t h) {
      g_hash_acc ^= h + 0x9e3779b97f4a7c15ull + (g_hash_acc << 6);
      g_hash_acc *= 0x100000001b3ull;
    };

  int ran = 0;
  for (auto& t : tests) {
    // wdog_selftest_* deliberately wedge to prove the watchdog fires; they
    // run only when named explicitly (tests/test_cpp_suite.py does).
    bool selected = argc <= 1 && std::strncmp(t.name, "wdog_selftest", 13) != 0;
    for (int i = 1; i < argc; i++)
      if (std::strcmp(argv[i], t.name) == 0) selected = true;
    if (!selected) continue;
    for (int r = 0; r < reruns; r++) {
      uint64_t s = seed + r;
      if (check_det) {
        g_hash_acc = 0;
        run_once(t, s);
        uint64_t h1 = g_hash_acc;
        g_hash_acc = 0;
        run_once(t, s);
        if (g_hash_acc != h1) {
          std::fprintf(stderr,
                       "[ DET! ] %s: two runs with seed %llu produced "
                       "different event traces\n",
                       t.name, (unsigned long long)s);
          return 3;
        }
      } else {
        run_once(t, s);
      }
    }
    ran++;
  }
  if (ran == 0) {
    std::fprintf(stderr, "no matching test\n");
    return 2;
  }
  return 0;
}
