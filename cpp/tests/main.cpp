// Test runner: ./madtpu_tests [--list | test_name ...]; no args = run all.
// Env: MADTPU_TEST_SEED (replay), MADTPU_TEST_NUM (reruns with fresh seeds),
// MADTPU_TEST_CHECK_DETERMINISTIC=1 (double-run; relies on each test
// creating one simcore::Sim and the runner comparing its trace hash —
// the analogue of the reference's double-run determinism check).
#include <chrono>
#include <cstring>

#include "framework.h"

int main(int argc, char** argv) {
  auto& tests = mtest::registry();
  if (argc > 1 && std::strcmp(argv[1], "--list") == 0) {
    for (auto& t : tests) std::printf("%s\n", t.name);
    return 0;
  }
  uint64_t seed;
  const char* env_seed = std::getenv("MADTPU_TEST_SEED");
  if (env_seed)
    seed = std::strtoull(env_seed, nullptr, 10);
  else
    seed = (uint64_t)std::chrono::steady_clock::now().time_since_epoch().count();
  int reruns = 1;
  if (const char* n = std::getenv("MADTPU_TEST_NUM")) reruns = std::atoi(n);

  int ran = 0;
  for (auto& t : tests) {
    bool selected = argc <= 1;
    for (int i = 1; i < argc; i++)
      if (std::strcmp(argv[i], t.name) == 0) selected = true;
    if (!selected) continue;
    for (int r = 0; r < reruns; r++) {
      uint64_t s = seed + r;
      std::printf("[ RUN  ] %s  MADTPU_TEST_SEED=%llu\n", t.name,
                  (unsigned long long)s);
      std::fflush(stdout);
      t.fn(s);
      std::printf("[ OK   ] %s\n", t.name);
      std::fflush(stdout);
    }
    ran++;
  }
  if (ran == 0) {
    std::fprintf(stderr, "no matching test\n");
    return 2;
  }
  return 0;
}
