// Lab 2 (Raft) suite — the 23 tests of the reference spec (SURVEY.md §4.1,
// /root/reference/src/raft/tests.rs) re-expressed against raft-core on
// simcore, plus a whole-scenario determinism check. Each test is a function
// of the seed; failures replay with MADTPU_TEST_SEED=<n>.
#include "../raftcore/raft_tester.h"
#include "framework.h"

using namespace raftcore;
using simcore::Sim;

namespace {

using TestBody = Task<void> (*)(RaftTester&);

Task<void> test_main(Sim* s, RaftTester* t, TestBody body) {
  co_await s->spawn(t->init());
  co_await s->spawn(body(*t));
  t->end();
}

void run_test(uint64_t seed, int n, bool unreliable, bool snapshot,
              TestBody body) {
  Sim sim(seed);
  RaftTester t(&sim, n, unreliable, snapshot);
  MT_ASSERT(sim.run(test_main(&sim, &t, body)));
}

uint64_t rnd(RaftTester& t) { return t.sim()->rand_u64() % 1000000000; }

constexpr size_t MAX_LOG_SIZE = 2000;  // tests.rs:859
constexpr uint64_t ANY_TERM = ~0ull;   // wait(): don't abort on term change

#define AW(expr) (co_await t.sim()->spawn((expr)))
#define TSLEEP(ns) co_await t.sim()->sleep(ns)

// ------------------------------------------------------------------ 2A

Task<void> b_initial_election(RaftTester& t) {
  AW(t.check_one_leader());
  TSLEEP(50 * MSEC);
  uint64_t term1 = AW(t.check_terms());
  MT_ASSERT(term1 >= 1);
  TSLEEP(2 * RAFT_ELECTION_TIMEOUT);
  AW(t.check_terms());  // term may not change, but must agree
  AW(t.check_one_leader());
}
MT_TEST(initial_election_2a) { run_test(seed, 3, false, false, b_initial_election); }

Task<void> b_reelection(RaftTester& t) {
  int leader1 = AW(t.check_one_leader());
  // leader disconnects: a new one appears
  t.disconnect(leader1);
  AW(t.check_one_leader());
  // old leader rejoins: doesn't disturb the new leader
  t.connect(leader1);
  int leader2 = AW(t.check_one_leader());
  // no quorum: no leader
  t.disconnect(leader2);
  t.disconnect((leader2 + 1) % 3);
  TSLEEP(2 * RAFT_ELECTION_TIMEOUT);
  AW(t.check_no_leader());
  // quorum restored
  t.connect((leader2 + 1) % 3);
  AW(t.check_one_leader());
  t.connect(leader2);
  AW(t.check_one_leader());
}
MT_TEST(reelection_2a) { run_test(seed, 3, false, false, b_reelection); }

Task<void> b_many_election(RaftTester& t) {
  AW(t.check_one_leader());
  for (int iters = 0; iters < 10; iters++) {
    int i1 = (int)(t.sim()->rand_u64() % 7);
    int i2 = (int)(t.sim()->rand_u64() % 7);
    int i3 = (int)(t.sim()->rand_u64() % 7);
    t.disconnect(i1);
    t.disconnect(i2);
    t.disconnect(i3);
    AW(t.check_one_leader());  // 4+ nodes remain: a leader must exist
    t.connect(i1);
    t.connect(i2);
    t.connect(i3);
  }
  AW(t.check_one_leader());
}
MT_TEST(many_election_2a) { run_test(seed, 7, false, false, b_many_election); }

// ------------------------------------------------------------------ 2B

Task<void> b_basic_agree(RaftTester& t) {
  for (uint64_t index = 1; index <= 3; index++) {
    auto [nd, val] = t.n_committed(index);
    MT_ASSERT_EQ(nd, 0);  // nothing committed yet
    uint64_t xindex = AW(t.one(index * 100, 5, false));
    MT_ASSERT_EQ(xindex, index);
  }
}
MT_TEST(basic_agree_2b) { run_test(seed, 5, false, false, b_basic_agree); }

Task<void> b_fail_agree(RaftTester& t) {
  AW(t.one(101, 3, false));
  // follower disconnects: progress with the remaining pair
  int leader = AW(t.check_one_leader());
  t.disconnect((leader + 1) % 3);
  AW(t.one(102, 2, false));
  AW(t.one(103, 2, false));
  TSLEEP(RAFT_ELECTION_TIMEOUT);
  AW(t.one(104, 2, false));
  AW(t.one(105, 2, false));
  // rejoin: it catches up
  t.connect((leader + 1) % 3);
  AW(t.one(106, 3, true));
  TSLEEP(RAFT_ELECTION_TIMEOUT);
  AW(t.one(107, 3, true));
}
MT_TEST(fail_agree_2b) { run_test(seed, 3, false, false, b_fail_agree); }

Task<void> b_fail_no_agree(RaftTester& t) {
  AW(t.one(10, 5, false));
  // 3 of 5 disconnect: no commit possible
  int leader = AW(t.check_one_leader());
  t.disconnect((leader + 1) % 5);
  t.disconnect((leader + 2) % 5);
  t.disconnect((leader + 3) % 5);
  auto r = t.raft(leader)->start(enc_u64(20));
  MT_ASSERT(r.ok);
  MT_ASSERT_EQ(r.index, 2u);
  TSLEEP(2 * RAFT_ELECTION_TIMEOUT);
  auto [nd, val] = t.n_committed(r.index);
  MT_ASSERT_EQ(nd, 0);  // no commit without a majority
  // heal; the index may be reused by the new leader
  t.connect((leader + 1) % 5);
  t.connect((leader + 2) % 5);
  t.connect((leader + 3) % 5);
  int leader2 = AW(t.check_one_leader());
  auto r2 = t.raft(leader2)->start(enc_u64(30));
  MT_ASSERT(r2.ok);
  MT_ASSERT(r2.index >= 2 && r2.index <= 3);
  AW(t.one(1000, 5, true));
}
MT_TEST(fail_no_agree_2b) { run_test(seed, 5, false, false, b_fail_no_agree); }

Task<void> b_concurrent_starts(RaftTester& t) {
  bool success = false;
  for (int try_ = 0; try_ < 5 && !success; try_++) {
    if (try_ > 0) TSLEEP(3 * SEC);
    int leader = AW(t.check_one_leader());
    auto r = t.raft(leader)->start(enc_u64(1));
    if (!r.ok) continue;  // leader moved on
    uint64_t term = r.term;
    std::vector<uint64_t> indices;
    bool failed = false;
    for (uint64_t i = 0; i < 5; i++) {  // 5 simultaneous start()s
      auto ri = t.raft(leader)->start(enc_u64(100 + i));
      if (!ri.ok || ri.term != term) {
        failed = true;
        break;
      }
      indices.push_back(ri.index);
    }
    if (failed) continue;
    std::vector<uint64_t> cmds;
    for (uint64_t idx : indices) {
      auto v = AW(t.wait(idx, 3, term));
      if (!v) {
        failed = true;  // term changed mid-agreement: retry whole round
        break;
      }
      cmds.push_back(*v);
    }
    if (failed) continue;
    for (uint64_t i = 0; i < 5; i++) {
      bool found = false;
      for (uint64_t c : cmds)
        if (c == 100 + i) found = true;
      MT_ASSERT(found);  // every concurrent start committed, in this term
    }
    success = true;
  }
  MT_ASSERT(success);
}
MT_TEST(concurrent_starts_2b) { run_test(seed, 3, false, false, b_concurrent_starts); }

Task<void> b_rejoin(RaftTester& t) {
  AW(t.one(101, 3, true));
  // leader goes into a minority with uncommitted entries
  int leader1 = AW(t.check_one_leader());
  t.disconnect(leader1);
  t.raft(leader1)->start(enc_u64(102));
  t.raft(leader1)->start(enc_u64(103));
  t.raft(leader1)->start(enc_u64(104));
  // new leader commits at index 2
  AW(t.one(103, 2, true));
  // new leader into a minority; old leader rejoins and is overwritten
  int leader2 = AW(t.check_one_leader());
  t.disconnect(leader2);
  t.connect(leader1);
  AW(t.one(104, 2, true));
  t.connect(leader2);
  AW(t.one(105, 3, true));
}
MT_TEST(rejoin_2b) { run_test(seed, 3, false, false, b_rejoin); }

Task<void> b_backup(RaftTester& t) {
  AW(t.one(rnd(t), 5, true));
  // leader + one follower isolated with a pile of uncommitted entries
  int leader1 = AW(t.check_one_leader());
  t.disconnect((leader1 + 2) % 5);
  t.disconnect((leader1 + 3) % 5);
  t.disconnect((leader1 + 4) % 5);
  for (int i = 0; i < 50; i++) t.raft(leader1)->start(enc_u64(rnd(t)));
  TSLEEP(RAFT_ELECTION_TIMEOUT / 2);
  t.disconnect((leader1 + 0) % 5);
  t.disconnect((leader1 + 1) % 5);
  // the other trio commits 50
  t.connect((leader1 + 2) % 5);
  t.connect((leader1 + 3) % 5);
  t.connect((leader1 + 4) % 5);
  for (int i = 0; i < 50; i++) AW(t.one(rnd(t), 3, true));
  // new leader + one follower isolated with uncommitted entries
  int leader2 = AW(t.check_one_leader());
  int other = (leader1 + 2) % 5;
  if (leader2 == other) other = (leader2 + 1) % 5;
  t.disconnect(other);
  for (int i = 0; i < 50; i++) t.raft(leader2)->start(enc_u64(rnd(t)));
  TSLEEP(RAFT_ELECTION_TIMEOUT / 2);
  // bring the original pair + `other` back: they must reconcile fast
  for (int i = 0; i < 5; i++) t.disconnect(i);
  t.connect((leader1 + 0) % 5);
  t.connect((leader1 + 1) % 5);
  t.connect(other);
  for (int i = 0; i < 50; i++) AW(t.one(rnd(t), 3, true));
  for (int i = 0; i < 5; i++) t.connect(i);
  AW(t.one(rnd(t), 5, true));
}
MT_TEST(backup_2b) { run_test(seed, 5, false, false, b_backup); }

Task<void> b_count(RaftTester& t) {
  // election budget (tests.rs:397-401)
  AW(t.check_one_leader());
  uint64_t total1 = t.rpcs();
  MT_ASSERT(total1 >= 1 && total1 <= 30);

  const uint64_t iters = 10;
  bool success = false;
  for (int try_ = 0; try_ < 5 && !success; try_++) {
    if (try_ > 0) TSLEEP(3 * SEC);
    int leader = AW(t.check_one_leader());
    uint64_t before = t.rpcs();
    auto r = t.raft(leader)->start(enc_u64(1));
    if (!r.ok) continue;
    std::vector<uint64_t> cmds;
    bool failed = false;
    for (uint64_t i = 1; i <= iters; i++) {
      uint64_t x = t.sim()->rand_u64() % 1000000;
      cmds.push_back(x);
      auto ri = t.raft(leader)->start(enc_u64(x));
      if (!ri.ok || ri.term != r.term) {
        failed = true;
        break;
      }
      MT_ASSERT_EQ(ri.index, r.index + i);
    }
    if (failed) continue;
    for (uint64_t i = 1; i <= iters; i++) {
      auto v = AW(t.wait(r.index + i, 3, r.term));
      if (!v) {
        failed = true;
        break;
      }
      MT_ASSERT_EQ(*v, cmds[i - 1]);
    }
    if (failed) continue;
    // agreement budget (tests.rs:461-462)
    uint64_t total2 = t.rpcs() - before;
    MT_ASSERT(total2 <= (iters + 1 + 3) * 3);
    success = true;
  }
  MT_ASSERT(success);
  // idle budget (tests.rs:470-476)
  TSLEEP(1 * SEC);
  uint64_t total3 = t.rpcs();
  TSLEEP(1 * SEC);
  MT_ASSERT(t.rpcs() - total3 <= 3 * 20);
}
MT_TEST(count_2b) { run_test(seed, 3, false, false, b_count); }

// ------------------------------------------------------------------ 2C

Task<void> b_persist1(RaftTester& t) {
  AW(t.one(11, 3, true));
  // crash+restart everyone
  for (int i = 0; i < 3; i++) t.crash1(i);
  for (int i = 0; i < 3; i++) {
    AW(t.start1(i));
    t.connect(i);
  }
  AW(t.one(12, 3, true));
  int leader1 = AW(t.check_one_leader());
  t.disconnect(leader1);
  t.crash1(leader1);
  AW(t.start1(leader1));
  t.connect(leader1);
  AW(t.one(13, 3, true));
  int leader2 = AW(t.check_one_leader());
  t.crash1(leader2);
  AW(t.one(14, 2, true));
  AW(t.start1(leader2));
  t.connect(leader2);
  AW(t.wait(4, 3, ANY_TERM));  // restarted leader catches up
  int i3 = (AW(t.check_one_leader()) + 1) % 3;
  t.crash1(i3);
  AW(t.one(15, 2, true));
  AW(t.start1(i3));
  t.connect(i3);
  AW(t.one(16, 3, true));
}
MT_TEST(persist1_2c) { run_test(seed, 3, false, false, b_persist1); }

Task<void> b_persist2(RaftTester& t) {
  uint64_t index = 1;
  for (int iters = 0; iters < 5; iters++) {
    AW(t.one(10 + index, 5, true));
    index++;
    int leader1 = AW(t.check_one_leader());
    t.crash1((leader1 + 1) % 5);
    t.crash1((leader1 + 2) % 5);
    AW(t.one(10 + index, 3, true));
    index++;
    t.crash1((leader1 + 0) % 5);
    t.crash1((leader1 + 3) % 5);
    t.crash1((leader1 + 4) % 5);
    AW(t.start1((leader1 + 1) % 5));
    t.connect((leader1 + 1) % 5);
    AW(t.start1((leader1 + 2) % 5));
    t.connect((leader1 + 2) % 5);
    TSLEEP(RAFT_ELECTION_TIMEOUT);
    AW(t.start1((leader1 + 3) % 5));
    t.connect((leader1 + 3) % 5);
    AW(t.one(10 + index, 3, true));
    index++;
    AW(t.start1((leader1 + 4) % 5));
    t.connect((leader1 + 4) % 5);
    AW(t.start1((leader1 + 0) % 5));
    t.connect((leader1 + 0) % 5);
  }
  AW(t.one(1000, 5, true));
}
MT_TEST(persist2_2c) { run_test(seed, 5, false, false, b_persist2); }

Task<void> b_persist3(RaftTester& t) {
  AW(t.one(101, 3, true));
  int leader = AW(t.check_one_leader());
  t.disconnect((leader + 2) % 3);
  AW(t.one(102, 2, true));
  // crash both members of the pair that made progress
  t.crash1((leader + 0) % 3);
  t.crash1((leader + 1) % 3);
  t.connect((leader + 2) % 3);
  AW(t.start1((leader + 0) % 3));
  t.connect((leader + 0) % 3);
  AW(t.one(103, 2, true));
  AW(t.start1((leader + 1) % 3));
  t.connect((leader + 1) % 3);
  AW(t.one(104, 3, true));
}
MT_TEST(persist3_2c) { run_test(seed, 3, false, false, b_persist3); }

Task<void> b_figure8(RaftTester& t) {
  // Raft Figure 8: repeatedly crash leaders with in-flight entries; no
  // committed entry may ever be lost (tests.rs:612-660).
  AW(t.one(rnd(t), 1, true));
  int nup = 5;
  for (int iters = 0; iters < 1000; iters++) {
    int leader = -1;
    for (int i = 0; i < 5; i++) {
      if (t.raft(i)) {
        auto r = t.raft(i)->start(enc_u64(rnd(t)));
        if (r.ok) leader = i;
      }
    }
    if (t.sim()->rand_u64() % 1000 < 100)
      TSLEEP(t.sim()->rand_u64() % (RAFT_ELECTION_TIMEOUT / 2));
    else
      TSLEEP(t.sim()->rand_u64() % (13 * MSEC));
    if (leader != -1) {
      t.crash1(leader);
      nup--;
    }
    if (nup < 3) {
      int s = (int)(t.sim()->rand_u64() % 5);
      if (!t.raft(s)) {
        AW(t.start1(s));
        t.connect(s);
        nup++;
      }
    }
  }
  for (int i = 0; i < 5; i++) {
    if (!t.raft(i)) {
      AW(t.start1(i));
      t.connect(i);
    }
  }
  AW(t.one(rnd(t), 5, true));
}
MT_TEST(figure_8_2c) { run_test(seed, 5, false, false, b_figure8); }

Task<void> b_unreliable_agree(RaftTester& t) {
  std::vector<simcore::TaskRef<uint64_t>> refs;
  for (uint64_t iters = 1; iters < 50; iters++) {
    for (uint64_t j = 0; j < 4; j++)
      refs.push_back(t.sim()->spawn(t.one(100 * iters + j, 1, true)));
    AW(t.one(iters, 1, true));
  }
  for (auto& r : refs) co_await r;
  t.set_unreliable(false);
  TSLEEP(RAFT_ELECTION_TIMEOUT);
  AW(t.one(100, 5, true));
}
MT_TEST(unreliable_agree_2c) { run_test(seed, 5, true, false, b_unreliable_agree); }

Task<void> b_figure8_unreliable(RaftTester& t) {
  AW(t.one(rnd(t) % 10000, 1, true));
  int nup = 5;
  for (int iters = 0; iters < 1000; iters++) {
    if (iters == 200) {
      // crank up delay variance mid-run (the reference enables long
      // reordering here, tests.rs:689)
      t.sim()->net_config().send_latency_max = 60 * MSEC;
    }
    int leader = -1;
    for (int i = 0; i < 5; i++) {
      auto r = t.raft(i)->start(enc_u64(rnd(t) % 10000));
      if (r.ok && t.is_connected(i)) leader = i;
    }
    if (t.sim()->rand_u64() % 1000 < 100)
      TSLEEP(t.sim()->rand_u64() % (RAFT_ELECTION_TIMEOUT / 2));
    else
      TSLEEP(t.sim()->rand_u64() % (13 * MSEC));
    if (leader != -1 && t.sim()->rand_u64() % 1000 < 500) {
      t.disconnect(leader);
      nup--;
    }
    if (nup < 3) {
      int s = (int)(t.sim()->rand_u64() % 5);
      if (!t.is_connected(s)) {
        t.connect(s);
        nup++;
      }
    }
  }
  for (int i = 0; i < 5; i++) t.connect(i);
  AW(t.one(rnd(t) % 10000, 5, true));
}
MT_TEST(figure_8_unreliable_2c) { run_test(seed, 5, true, false, b_figure8_unreliable); }

// churn: concurrent clients race random crash/restart/disconnect storms;
// every value a client observed as committed must be in the final log
// (tests.rs:744-856)
struct ChurnShared {
  bool stop = false;
  std::vector<uint64_t> values[3];
};

Task<void> churn_client(RaftTester* t, int me, std::shared_ptr<ChurnShared> sh) {
  while (!sh->stop) {
    uint64_t x = t->sim()->rand_u64();
    int start_i = (int)(t->sim()->rand_u64() % t->n());
    std::optional<uint64_t> index;
    for (int off = 0; off < t->n(); off++) {
      int i = (start_i + off) % t->n();
      if (!t->raft(i)) continue;
      auto r = t->raft(i)->start(enc_u64(x));
      if (r.ok) {
        index = r.index;
        break;
      }
    }
    if (index) {
      for (uint64_t to = 10 * MSEC; to <= 320 * MSEC; to *= 2) {
        auto [nd, val] = t->n_committed(*index);
        if (nd > 0) {
          if (val && *val == x) sh->values[me].push_back(x);
          break;
        }
        co_await t->sim()->sleep(to);
      }
    } else {
      co_await t->sim()->sleep((79 + me * 17) * MSEC);
    }
  }
}

Task<void> b_churn(RaftTester& t) {
  AW(t.one(rnd(t), 1, true));
  auto sh = std::make_shared<ChurnShared>();
  std::vector<simcore::TaskRef<void>> clients;
  for (int me = 0; me < 3; me++)
    clients.push_back(
        t.sim()->spawn(make_addr(0, 0, 2, me + 1), churn_client(&t, me, sh)));
  for (int iters = 0; iters < 20; iters++) {
    if (t.sim()->rand_u64() % 1000 < 200) {
      int i = (int)(t.sim()->rand_u64() % 5);
      t.disconnect(i);
    }
    if (t.sim()->rand_u64() % 1000 < 500) {
      int i = (int)(t.sim()->rand_u64() % 5);
      if (!t.raft(i)) AW(t.start1(i));
      t.connect(i);
    }
    if (t.sim()->rand_u64() % 1000 < 200) {
      int i = (int)(t.sim()->rand_u64() % 5);
      if (t.raft(i)) t.crash1(i);
    }
    TSLEEP(RAFT_ELECTION_TIMEOUT * 7 / 10);
  }
  TSLEEP(RAFT_ELECTION_TIMEOUT);
  t.set_unreliable(false);
  for (int i = 0; i < 5; i++) {
    if (!t.raft(i)) AW(t.start1(i));
    t.connect(i);
  }
  sh->stop = true;
  for (auto& c : clients) co_await c;
  uint64_t last_index = AW(t.one(rnd(t), 5, true));
  // collect the final committed log and verify every client-observed commit
  std::vector<uint64_t> really;
  for (uint64_t idx = 1; idx <= last_index; idx++) {
    auto [nd, val] = t.n_committed(idx);
    MT_ASSERT(nd > 0);
    really.push_back(*val);
  }
  for (int me = 0; me < 3; me++) {
    for (uint64_t v : sh->values[me]) {
      bool found = false;
      for (uint64_t rv : really)
        if (rv == v) found = true;
      MT_ASSERT(found);  // an observed commit vanished
    }
  }
}
MT_TEST(reliable_churn_2c) { run_test(seed, 5, false, false, b_churn); }
MT_TEST(unreliable_churn_2c) { run_test(seed, 5, true, false, b_churn); }

// ------------------------------------------------------------------ 2D

Task<void> snap_common(RaftTester& t, bool disconnect_, bool reliable,
                       bool crash) {
  const int servers = 3;
  t.set_unreliable(!reliable);
  AW(t.one(rnd(t), servers, true));
  int leader1 = AW(t.check_one_leader());
  for (int i = 0; i < 30; i++) {
    int victim = (leader1 + 1) % servers;
    int sender = leader1;
    if (i % 3 == 1) {
      sender = (leader1 + 1) % servers;
      victim = leader1;
    }
    if (disconnect_) {
      t.disconnect(victim);
      AW(t.one(rnd(t), servers - 1, true));
    }
    if (crash) {
      t.crash1(victim);
      AW(t.one(rnd(t), servers - 1, true));
    }
    // push enough entries that a snapshot must happen while victim is away
    int nn = (int)(SNAPSHOT_INTERVAL / 2 + t.sim()->rand_u64() % SNAPSHOT_INTERVAL);
    for (int j = 0; j < nn; j++)
      if (t.raft(sender)) t.raft(sender)->start(enc_u64(rnd(t)));
    if (disconnect_ || crash)
      AW(t.one(rnd(t), servers - 1, true));
    else
      AW(t.one(rnd(t), servers, true));
    MT_ASSERT(t.log_size() < MAX_LOG_SIZE);  // compaction is working
    if (disconnect_) {
      // reconnect: catch-up must go through InstallSnapshot
      t.connect(victim);
      AW(t.one(rnd(t), servers, true));
      leader1 = AW(t.check_one_leader());
    }
    if (crash) {
      AW(t.start1(victim));
      t.connect(victim);
      AW(t.one(rnd(t), servers, true));
      leader1 = AW(t.check_one_leader());
    }
  }
}

Task<void> b_snap_basic(RaftTester& t) { co_await t.sim()->spawn(snap_common(t, false, true, false)); }
Task<void> b_snap_install(RaftTester& t) { co_await t.sim()->spawn(snap_common(t, true, true, false)); }
Task<void> b_snap_install_unreliable(RaftTester& t) { co_await t.sim()->spawn(snap_common(t, true, false, false)); }
Task<void> b_snap_install_crash(RaftTester& t) { co_await t.sim()->spawn(snap_common(t, false, true, true)); }
Task<void> b_snap_install_unreliable_crash(RaftTester& t) { co_await t.sim()->spawn(snap_common(t, false, false, true)); }

MT_TEST(snapshot_basic_2d) { run_test(seed, 3, false, true, b_snap_basic); }
MT_TEST(snapshot_install_2d) { run_test(seed, 3, false, true, b_snap_install); }
MT_TEST(snapshot_install_unreliable_2d) {
  run_test(seed, 3, true, true, b_snap_install_unreliable);
}
MT_TEST(snapshot_install_crash_2d) { run_test(seed, 3, false, true, b_snap_install_crash); }
MT_TEST(snapshot_install_unreliable_crash_2d) {
  run_test(seed, 3, true, true, b_snap_install_unreliable_crash);
}

// ---------------------------------------------------- determinism (ours)
// A full faulty scenario run twice from one seed must produce the identical
// event trace — the MADTPU_TEST_CHECK_DETERMINISTIC foundation
// (reference README.md:81-87).

Task<void> b_det_scenario(RaftTester& t) {
  AW(t.one(1, 3, true));
  int leader = AW(t.check_one_leader());
  t.disconnect((leader + 1) % 3);
  AW(t.one(2, 2, true));
  t.connect((leader + 1) % 3);
  t.crash1(leader);
  AW(t.start1(leader));
  t.connect(leader);
  AW(t.one(3, 3, true));
}

static std::pair<uint64_t, uint64_t> det_run(uint64_t seed) {
  Sim sim(seed);
  RaftTester t(&sim, 3, true, false);
  MT_ASSERT(sim.run(test_main(&sim, &t, b_det_scenario)));
  return {sim.trace_hash(), sim.msg_count()};
}

MT_TEST(raft_determinism) {
  auto a = det_run(seed);
  auto b = det_run(seed);
  auto c = det_run(seed + 1);
  MT_ASSERT_EQ(a.first, b.first);
  MT_ASSERT_EQ(a.second, b.second);
  MT_ASSERT(a.first != c.first);
}

}  // namespace
