// Lab 4B (sharded KV) suite — the 13 tests of the reference spec
// (SURVEY.md §4.4, /root/reference/src/shardkv/tests.rs) re-expressed against
// the shardkv layer on simcore: static sharding, join/leave migration,
// snapshots, missed config changes, concurrent append storms racing
// reconfiguration and group-wide crashes, unreliable nets, challenge 1
// (shard deletion storage bound) and challenge 2 (availability of
// unaffected / partially-migrated shards). unreliable3_4b — #[ignore]d
// upstream as a linearizability TODO (tests.rs:431) — is implemented here
// with the Wing-Gong checker (kvraft/linearize.h) over recorded histories.
//
// NOTE: no braced-init-list may appear in a statement containing co_await
// (gcc 12 "array used as initializer"); helpers below keep braces out.
#include <cstdio>
#include <memory>

#include "../kvraft/linearize.h"
#include "../shardkv/shardkv_tester.h"
#include "framework.h"

using namespace shardkv;
using simcore::Sim;
using simcore::TaskRef;
using simcore::MSEC;
using simcore::SEC;

namespace {

using Kvs = ShardKvTester::Clerk::Kvs;

Kvs make_kvs(Sim* sim, int n, size_t len) {
  Kvs kvs;
  for (int i = 0; i < n; i++)
    kvs.emplace_back(std::to_string(i), ShardKvTester::rand_string(sim, len));
  return kvs;
}

// ---- spawn_concurrent_append (tests.rs:194-220): per-key clerks append
// random suffixes until stopped; collect the predicted final values.
struct ConcurrentAppend {
  std::shared_ptr<bool> done = std::make_shared<bool>(false);
  std::vector<TaskRef<std::pair<std::string, std::string>>> handles;

  Task<Kvs> stop() {
    *done = true;
    Kvs kvs;
    for (auto& h : handles) kvs.push_back(co_await h);
    co_return kvs;
  }
};

Task<std::pair<std::string, std::string>> append_loop(
    Sim* sim, ShardKvTester::Clerk ck, std::string k, std::string v,
    size_t len, uint64_t sleep_ms, std::shared_ptr<bool> done) {
  while (!*done) {
    auto s = ShardKvTester::rand_string(sim, len);
    v += s;
    co_await ck.append(k, s);
    co_await sim->sleep(sleep_ms * MSEC);
  }
  std::pair<std::string, std::string> out(std::move(k), std::move(v));
  co_return out;
}

ConcurrentAppend spawn_concurrent_append(Sim* sim, ShardKvTester& t,
                                         const Kvs& kvs, size_t len,
                                         uint64_t sleep_ms) {
  ConcurrentAppend ca;
  for (auto& [k, v] : kvs)
    ca.handles.push_back(sim->spawn(
        append_loop(sim, t.make_client(), k, v, len, sleep_ms, ca.done)));
  return ca;
}

// ---- static_shards_4b (tests.rs:18-67)
Task<void> static_shards_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::nullopt);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();

  co_await t.join(0);
  co_await t.join(1);

  Kvs kvs = make_kvs(sim, 10, 20);
  co_await ck.put_kvs(kvs);
  co_await ck.check_kvs(kvs);

  // shut down one group; exactly half the Gets may complete (tests.rs:39-60)
  t.shutdown_group(1);
  t.check_logs();  // forbid snapshots when max_raft_state is None

  auto ndone = std::make_shared<int>(0);
  std::vector<TaskRef<void>> handles;
  for (auto& [k, v] : kvs) {
    auto one = [](ShardKvTester::Clerk c, std::string k2, std::string v2,
                  std::shared_ptr<int> n) -> Task<void> {
      co_await c.check(std::move(k2), std::move(v2));
      ++*n;
    };
    handles.push_back(sim->spawn(one(t.make_client(), k, v, ndone)));
  }
  co_await sim->sleep(2 * SEC);
  for (auto& h : handles) h.abort();  // drop(handles), tests.rs:55
  MT_ASSERT_EQ(*ndone, 5);

  co_await sim->spawn(t.start_group(1));
  co_await ck.check_kvs(kvs);
  t.end();
}

// ---- join_leave_4b (tests.rs:69-99)
Task<void> join_leave_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::nullopt);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 10, 5);
  co_await ck.put_kvs(kvs);
  co_await ck.check_kvs(kvs);

  co_await t.join(1);
  co_await ck.check_append_kvs(kvs, 5);
  co_await t.leave(0);
  co_await ck.check_append_kvs(kvs, 5);

  co_await sim->sleep(1 * SEC);  // allow time for shards to transfer
  t.check_logs();
  t.shutdown_group(0);
  co_await ck.check_kvs(kvs);
  t.end();
}

// ---- snapshot_4b (tests.rs:101-141)
Task<void> snapshot_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::nullopt);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 30, 20);
  co_await ck.put_kvs(kvs);
  co_await ck.check_kvs(kvs);

  co_await t.join(1);
  co_await t.join(2);
  co_await t.leave(0);
  co_await ck.check_append_kvs(kvs, 20);

  co_await t.leave(1);
  co_await t.join(0);
  co_await ck.check_append_kvs(kvs, 20);

  co_await sim->sleep(1 * SEC);
  co_await ck.check_kvs(kvs);
  co_await sim->sleep(1 * SEC);
  t.check_logs();

  for (int i = 0; i < 3; i++) t.shutdown_group(i);
  for (int i = 0; i < 3; i++) co_await sim->spawn(t.start_group(i));
  co_await ck.check_kvs(kvs);
  t.end();
}

// ---- miss_change_4b (tests.rs:143-191)
Task<void> miss_change_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::optional<size_t>(1000));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 10, 20);
  co_await ck.put_kvs(kvs);
  co_await ck.check_kvs(kvs);

  co_await t.join(1);
  for (int i = 0; i < 3; i++) t.shutdown_server(i, 0);
  co_await t.join(2);
  co_await t.leave(0);
  co_await t.leave(1);
  co_await ck.check_append_kvs(kvs, 20);

  co_await t.join(1);
  co_await ck.check_append_kvs(kvs, 20);

  for (int i = 0; i < 3; i++) co_await sim->spawn(t.start_server(i, 0));
  co_await ck.check_append_kvs(kvs, 20);

  co_await sim->sleep(2 * SEC);
  for (int i = 0; i < 3; i++) t.shutdown_server(i, 1);
  co_await t.join(0);
  co_await t.leave(2);
  co_await ck.check_append_kvs(kvs, 20);

  for (int i = 0; i < 3; i++) co_await sim->spawn(t.start_server(i, 1));
  co_await ck.check_kvs(kvs);
  t.end();
}

// ---- concurrent1_4b (tests.rs:222-272)
Task<void> concurrent1_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::optional<size_t>(100));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 10, 5);
  co_await ck.put_kvs(kvs);
  co_await ck.check_kvs(kvs);

  auto ca = spawn_concurrent_append(sim, t, kvs, 5, 10);

  co_await sim->sleep(150 * MSEC);
  co_await t.join(1);
  co_await sim->sleep(500 * MSEC);
  co_await t.join(2);
  co_await sim->sleep(500 * MSEC);
  co_await t.leave(0);

  t.shutdown_group(0);
  co_await sim->sleep(100 * MSEC);
  t.shutdown_group(1);
  co_await sim->sleep(100 * MSEC);
  t.shutdown_group(2);

  co_await t.leave(2);

  co_await sim->sleep(100 * MSEC);
  for (int i = 0; i < 3; i++) co_await sim->spawn(t.start_group(i));

  co_await sim->sleep(100 * MSEC);
  co_await t.join(0);
  co_await t.leave(1);
  co_await sim->sleep(500 * MSEC);
  co_await t.join(1);

  co_await sim->sleep(1 * SEC);
  Kvs final_kvs = co_await ca.stop();
  co_await ck.check_kvs(final_kvs);
  t.end();
}

// ---- concurrent2_4b (tests.rs:274-318)
Task<void> concurrent2_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::nullopt);
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  for (int i = 0; i < 3; i++) co_await t.join(i);

  Kvs kvs = make_kvs(sim, 10, 1);
  co_await ck.put_kvs(kvs);

  auto ca = spawn_concurrent_append(sim, t, kvs, 1, 50);

  co_await t.leave(0);
  co_await t.leave(2);
  co_await sim->sleep(3 * SEC);
  co_await t.join(0);
  co_await t.join(2);
  co_await t.leave(1);
  co_await sim->sleep(3 * SEC);
  co_await t.join(1);
  co_await t.leave(0);
  co_await t.leave(2);
  co_await sim->sleep(3 * SEC);

  t.shutdown_group(1);
  t.shutdown_group(2);
  co_await sim->sleep(1 * SEC);
  co_await sim->spawn(t.start_group(1));
  co_await sim->spawn(t.start_group(2));

  co_await sim->sleep(2 * SEC);
  Kvs final_kvs = co_await ca.stop();
  co_await ck.check_kvs(final_kvs);
  t.end();
}

// ---- concurrent3_4b (tests.rs:320-362)
Task<void> concurrent3_main(Sim* sim) {
  ShardKvTester t(sim, 3, false, std::optional<size_t>(300));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 10, 1);
  co_await ck.put_kvs(kvs);

  auto ca = spawn_concurrent_append(sim, t, kvs, 1, 0);

  uint64_t t0 = sim->now();
  while (sim->now() - t0 < 12 * SEC) {
    co_await t.join(1);
    co_await t.join(2);
    co_await sim->sleep(sim->rand_range(0, 900) * MSEC);
    for (int i = 0; i < 3; i++) t.shutdown_group(i);
    for (int i = 0; i < 3; i++) co_await sim->spawn(t.start_group(i));

    co_await sim->sleep(sim->rand_range(0, 900) * MSEC);
    co_await t.leave(1);
    co_await t.leave(2);
    co_await sim->sleep(sim->rand_range(0, 900) * MSEC);
  }

  co_await sim->sleep(2 * SEC);
  Kvs final_kvs = co_await ca.stop();
  co_await ck.check_kvs(final_kvs);
  t.end();
}

// ---- unreliable1_4b (tests.rs:364-390)
Task<void> unreliable1_main(Sim* sim) {
  ShardKvTester t(sim, 3, true, std::optional<size_t>(100));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 10, 5);
  co_await ck.put_kvs(kvs);

  co_await t.join(1);
  co_await t.join(2);
  co_await t.leave(0);
  co_await ck.check_append_kvs(kvs, 5);
  co_await ck.check_append_kvs(kvs, 5);

  co_await t.join(0);
  co_await t.leave(1);
  co_await ck.check_kvs(kvs);
  t.end();
}

// ---- unreliable2_4b (tests.rs:392-427)
Task<void> unreliable2_main(Sim* sim) {
  ShardKvTester t(sim, 3, true, std::optional<size_t>(100));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs = make_kvs(sim, 10, 5);
  co_await ck.put_kvs(kvs);

  auto ca = spawn_concurrent_append(sim, t, kvs, 5, 0);

  co_await sim->sleep(150 * MSEC);
  co_await t.join(1);
  co_await sim->sleep(500 * MSEC);
  co_await t.join(2);
  co_await sim->sleep(500 * MSEC);
  co_await t.leave(0);
  co_await sim->sleep(500 * MSEC);
  co_await t.leave(1);
  co_await sim->sleep(500 * MSEC);
  co_await t.join(1);
  co_await t.join(0);

  co_await sim->sleep(2 * SEC);
  Kvs final_kvs = co_await ca.stop();
  co_await ck.check_kvs(final_kvs);
  t.end();
}

// ---- unreliable3_4b (tests.rs:429-433, #[ignore]d TODO upstream): full
// linearizability of mixed get/put/append histories under an unreliable net
// racing join/leave migration. Clerks record (invoke, return, output) with
// virtual timestamps; the Wing-Gong checker (linearize.h, per-key
// P-compositional with memoization) validates the merged history.
Task<std::vector<kvraft::HistOp>> lin_client_loop(
    Sim* sim, ShardKvTester::Clerk ck, int id, std::shared_ptr<bool> done) {
  std::vector<kvraft::HistOp> hist;
  int i = 0;
  while (!*done) {
    kvraft::HistOp h;
    h.key = std::to_string(sim->rand_range(0, 3));
    uint64_t r = sim->rand_range(0, 10);
    h.invoke = sim->now();
    if (r < 4) {
      h.kind = kvraft::Op::Kind::Get;
      h.output = co_await ck.get(h.key);
    } else if (r < 8) {
      h.kind = kvraft::Op::Kind::Append;
      h.input = "c" + std::to_string(id) + "-" + std::to_string(i++) + ";";
      co_await ck.append(h.key, h.input);
    } else {
      h.kind = kvraft::Op::Kind::Put;
      h.input = "P" + std::to_string(id) + "-" + std::to_string(i++) + ";";
      co_await ck.put(h.key, h.input);
    }
    h.ret = sim->now();
    hist.push_back(std::move(h));
    co_await sim->sleep(20 * MSEC);
  }
  co_return hist;
}

Task<void> unreliable3_main(Sim* sim) {
  ShardKvTester t(sim, 3, true, std::optional<size_t>(100));
  co_await sim->spawn(t.init());
  co_await t.join(0);

  auto done = std::make_shared<bool>(false);
  std::vector<TaskRef<std::vector<kvraft::HistOp>>> clients;
  for (int c = 0; c < 4; c++)
    clients.push_back(
        sim->spawn(lin_client_loop(sim, t.make_client(), c, done)));

  // migration churn while the history accumulates (unreliable2's schedule)
  co_await sim->sleep(150 * MSEC);
  co_await t.join(1);
  co_await sim->sleep(500 * MSEC);
  co_await t.join(2);
  co_await sim->sleep(500 * MSEC);
  co_await t.leave(0);
  co_await sim->sleep(500 * MSEC);
  co_await t.leave(1);
  co_await sim->sleep(500 * MSEC);
  co_await t.join(1);
  co_await t.join(0);
  co_await sim->sleep(9 * SEC);  // settle: virtual time is free

  *done = true;
  std::vector<kvraft::HistOp> hist;
  for (auto& h : clients) {
    auto part = co_await h;
    for (auto& op : part) hist.push_back(std::move(op));
  }
  // anti-starvation floor, not a throughput bound: under this storm a single
  // op can legitimately burn seconds of virtual time in clerk timeouts
  MT_ASSERT(hist.size() >= 12);
  MT_ASSERT(kvraft::check_linearizable_kv(hist));
  std::printf("  ... linearizability checked over %zu ops\n", hist.size());
  t.end();
}

// ---- challenge1_delete_4b (tests.rs:435-493): shard GC storage bound
Task<void> challenge1_main(Sim* sim) {
  // max_raft_state=1 forces a snapshot after every log entry
  ShardKvTester t(sim, 3, false, std::optional<size_t>(1));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  const int n = 30;  // 30,000 bytes of total values
  Kvs kvs = make_kvs(sim, n, 1000);
  co_await ck.put_kvs(kvs);
  Kvs head(kvs.begin(), kvs.begin() + 3);
  co_await ck.check_kvs(head);

  for (int iters = 0; iters < 2; iters++) {
    co_await t.join(1);
    co_await t.leave(0);
    co_await t.join(2);
    co_await sim->sleep(3 * SEC);
    co_await ck.check_kvs(head);
    co_await t.leave(1);
    co_await t.join(0);
    co_await t.leave(2);
    co_await sim->sleep(3 * SEC);
    co_await ck.check_kvs(head);
  }

  co_await t.join(1);
  co_await t.join(2);
  for (int i = 0; i < 3; i++) {
    co_await sim->sleep(1 * SEC);
    co_await ck.check_kvs(head);
  }

  size_t total = t.total_size();
  // 27 keys stored once, 3 keys also in dup tables, ×3 replicas, plus slop
  // (tests.rs:477-488)
  size_t expected = 3 * ((n - 3) * 1000 + 2 * 3 * 1000 + 6000);
  if (total > expected) {
    std::fprintf(stderr, "persisted state too big: %zu > %zu\n", total,
                 expected);
    std::abort();
  }
  co_await ck.check_kvs(kvs);
  t.end();
}

// ---- challenge2_unaffected_4b (tests.rs:495-554)
Task<void> challenge2_unaffected_main(Sim* sim) {
  ShardKvTester t(sim, 3, true, std::optional<size_t>(100));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  co_await t.join(0);

  Kvs kvs;
  for (int i = 0; i < 10; i++) kvs.emplace_back(std::to_string(i), "100");
  co_await ck.put_kvs(kvs);

  co_await t.join(1);
  auto owned = co_await t.query_shards_of(1);

  // wait for migration + client config refresh; rewrite keys 101 now owns
  co_await sim->sleep(1 * SEC);
  for (auto& [k, v] : kvs) {
    if (owned.count(key2shard(k))) {
      v = "101";
      co_await ck.put(k, "101");
    }
  }

  t.shutdown_group(0);
  co_await t.leave(0);  // 101 can't migrate what 100 owned
  co_await sim->sleep(1 * SEC);

  // gets/puts for 101-owned keys must still complete
  for (auto& [k, v] : kvs) {
    if (owned.count(key2shard(k))) {
      co_await ck.check(k, v);
      co_await ck.put(k, v + "-1");
      co_await ck.check(k, v + "-1");
    }
  }
  t.end();
}

// ---- challenge2_partial_4b (tests.rs:556-605)
Task<void> challenge2_partial_main(Sim* sim) {
  ShardKvTester t(sim, 3, true, std::optional<size_t>(100));
  co_await sim->spawn(t.init());
  auto ck = t.make_client();
  std::vector<int> g012{0, 1, 2};
  co_await t.joins(g012);
  co_await sim->sleep(1 * SEC);

  Kvs kvs;
  for (int i = 0; i < 10; i++) kvs.emplace_back(std::to_string(i), "100");
  co_await ck.put_kvs(kvs);

  auto owned = co_await t.query_shards_of(2);

  t.shutdown_group(0);
  // 101 can pull old 102 shards, but not 100's; it must serve the former ASAP
  std::vector<int> g02{0, 2};
  co_await t.leaves(g02);
  co_await sim->sleep(1 * SEC);

  for (auto& [k, v] : kvs) {
    if (owned.count(key2shard(k))) {
      co_await ck.check(k, v);
      co_await ck.put(k, v + "-2");
      co_await ck.check(k, v + "-2");
    }
  }
  t.end();
}

}  // namespace

MT_TEST(shardkv_static_shards_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(static_shards_main(&sim)));
}
MT_TEST(shardkv_join_leave_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(join_leave_main(&sim)));
}
MT_TEST(shardkv_snapshot_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(snapshot_main(&sim)));
}
MT_TEST(shardkv_miss_change_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(miss_change_main(&sim)));
}
MT_TEST(shardkv_concurrent1_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(concurrent1_main(&sim)));
}
MT_TEST(shardkv_concurrent2_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(concurrent2_main(&sim)));
}
MT_TEST(shardkv_concurrent3_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(concurrent3_main(&sim)));
}
MT_TEST(shardkv_unreliable1_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(unreliable1_main(&sim)));
}
MT_TEST(shardkv_unreliable2_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(unreliable2_main(&sim)));
}
MT_TEST(shardkv_unreliable3_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(unreliable3_main(&sim)));
}
MT_TEST(shardkv_challenge1_delete_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(challenge1_main(&sim)));
}
MT_TEST(shardkv_challenge2_unaffected_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(challenge2_unaffected_main(&sim)));
}
MT_TEST(shardkv_challenge2_partial_4b) {
  Sim sim(seed);
  MT_ASSERT(sim.run(challenge2_partial_main(&sim)));
}
