// linearize — a KV linearizability checker for simulated histories.
//
// The reference ships linearizability variants of the Lab 3 tests but leaves
// them commented out (/root/reference/src/kvraft/tests.rs:386-390, 524-528);
// SURVEY.md §4.2/§7 directs this framework to implement them. This is a
// Wing & Gong search with the two standard refinements (the porcupine
// approach):
//   * P-compositionality: KV ops on distinct keys commute, so each key's
//     sub-history is checked independently.
//   * Memoization on (linearized-set, state): a (bitmask, value) pair that
//     failed once is never re-explored.
//
// History ops carry virtual invoke/return times from the simulator's clock;
// an op may take effect at any point between them. The test driver awaits
// every client before checking, so there are no pending (open) invocations.
#pragma once

#include <cstdint>
#include <cstdio>
#include <map>
#include <string>
#include <unordered_set>
#include <vector>

#include "kv.h"

namespace kvraft {

struct HistOp {
  uint64_t invoke = 0;  // virtual time the client issued the op
  uint64_t ret = 0;     // virtual time the reply arrived
  Op::Kind kind = Op::Kind::Get;
  std::string key;
  std::string input;   // Put/Append payload
  std::string output;  // Get reply
  HistOp() = default;
};

namespace lin_detail {

enum class LinResult { No, Yes, Inconclusive };

// Hard cap on explored search nodes: Wing-Gong is worst-case exponential, and
// a pathological history (many concurrent ops on one key) must fail CLEANLY
// as "inconclusive" rather than hang the suite or exhaust memory.
constexpr size_t MAX_VISITED = 4'000'000;

// Check one key's sub-history. ops.size() is bounded by the test driver;
// the bitmask is a vector<uint64_t>.
inline LinResult check_key(std::vector<HistOp> ops) {
  size_t n = ops.size();
  if (n == 0) return LinResult::Yes;
  size_t words = (n + 63) / 64;

  struct Node {
    std::vector<uint64_t> mask;  // linearized set
    std::string state;
    size_t count = 0;  // bits set in mask
  };

  // memo of visited (mask, state) configurations
  struct VHash {
    size_t operator()(const std::pair<std::vector<uint64_t>, std::string>& v)
        const {
      size_t h = 0xcbf29ce484222325ull;
      for (uint64_t w : v.first) {
        h ^= w;
        h *= 0x100000001b3ull;
      }
      for (char c : v.second) {
        h ^= uint8_t(c);
        h *= 0x100000001b3ull;
      }
      return h;
    }
  };
  std::unordered_set<std::pair<std::vector<uint64_t>, std::string>, VHash>
      seen;

  std::vector<Node> stack;
  stack.push_back(Node{std::vector<uint64_t>(words, 0), std::string(), 0});

  while (!stack.empty()) {
    if (seen.size() > MAX_VISITED) return LinResult::Inconclusive;
    Node cur = std::move(stack.back());
    stack.pop_back();
    if (cur.count == n) return LinResult::Yes;

    // earliest return among un-linearized ops: a candidate must invoke
    // before it (Wing-Gong minimality in the real-time partial order)
    uint64_t min_ret = ~0ull;
    for (size_t i = 0; i < n; i++) {
      if (cur.mask[i / 64] >> (i % 64) & 1) continue;
      if (ops[i].ret < min_ret) min_ret = ops[i].ret;
    }
    for (size_t i = 0; i < n; i++) {
      if (cur.mask[i / 64] >> (i % 64) & 1) continue;
      if (ops[i].invoke > min_ret) continue;  // not minimal: must come later
      // apply op i to cur.state
      std::string next_state = cur.state;
      switch (ops[i].kind) {
        case Op::Kind::Get:
          if (ops[i].output != cur.state) continue;  // inconsistent read
          break;
        case Op::Kind::Put:
          next_state = ops[i].input;
          break;
        case Op::Kind::Append:
          next_state += ops[i].input;
          break;
      }
      Node nxt;
      nxt.mask = cur.mask;
      nxt.mask[i / 64] |= 1ull << (i % 64);
      nxt.count = cur.count + 1;
      nxt.state = std::move(next_state);
      if (seen.emplace(nxt.mask, nxt.state).second)
        stack.push_back(std::move(nxt));
    }
  }
  return LinResult::No;
}

}  // namespace lin_detail

// True iff the whole history is linearizable (per-key decomposition). An
// inconclusive key (search-budget exhaustion) passes with a loud warning —
// a capped search must not produce a false FAILURE.
inline bool check_linearizable_kv(const std::vector<HistOp>& history) {
  std::map<std::string, std::vector<HistOp>> by_key;
  for (auto& op : history) by_key[op.key].push_back(op);
  for (auto& [key, ops] : by_key) {
    switch (lin_detail::check_key(ops)) {
      case lin_detail::LinResult::Yes:
        break;
      case lin_detail::LinResult::Inconclusive:
        std::fprintf(stderr,
                     "linearizability INCONCLUSIVE on key %s (%zu ops, search "
                     "budget exhausted)\n",
                     key.c_str(), ops.size());
        break;
      case lin_detail::LinResult::No:
        std::fprintf(stderr,
                     "linearizability violation on key %s (%zu ops)\n",
                     key.c_str(), ops.size());
        return false;
    }
  }
  return true;
}

}  // namespace kvraft
