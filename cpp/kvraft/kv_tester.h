// KvTester — cluster + clerk harness for the Lab 3 suite, the C++ analogue of
// the reference's kvraft tester (SURVEY.md §2 C12,
// /root/reference/src/kvraft/tester.rs):
//   * n KvServers at 0.0.1.(i+1); clerks at per-clerk sim addresses
//     0.0.2.(id+1) with selective visibility (tester.rs:129-150,214-221)
//   * pairwise partitioning partition(p1,p2) via connect2/disconnect2
//     (tester.rs:114-124)
//   * leader-in-minority partition builder make_partition (tester.rs:184-191)
//   * server restart via kill+respawn (tester.rs:153-169)
//   * metrics: log/snapshot size via fs file sizes (tester.rs:66-85),
//     op counter for the end-of-test stats (tester.rs:273-275)
#pragma once

#include <cstdio>
#include <memory>

#include "../tests/framework.h"
#include "kv.h"

namespace kvraft {

using simcore::make_addr;

constexpr uint64_t KV_ELECTION_TIMEOUT = 1 * SEC;  // tests.rs:16

class KvTester {
 public:
  KvTester(Sim* sim, int n, bool unreliable, std::optional<size_t> maxraftstate)
      : sim_(sim), n_(n), maxraftstate_(maxraftstate) {
    for (int i = 0; i < n; i++) addrs_.push_back(make_addr(0, 0, 1, i + 1));
    servers_.resize(n);
    auto& cfg = sim_->net_config();
    if (unreliable) {  // tester.rs:30-33
      cfg.packet_loss_rate = 0.1;
      cfg.send_latency_min = 1 * MSEC;
      cfg.send_latency_max = 27 * MSEC;
    }
    start_time_ = sim->now();
  }

  Task<void> init() {
    for (int i = 0; i < n_; i++) co_await sim_->spawn(start_server(i));
  }

  Sim* sim() { return sim_; }
  int n() const { return n_; }
  std::vector<int> all() const {
    std::vector<int> v(n_);
    for (int i = 0; i < n_; i++) v[i] = i;
    return v;
  }

  // ---- servers (tester.rs:153-169)
  Task<void> start_server(int i) {
    servers_[i] = co_await sim_->spawn(
        addrs_[i], KvServer::boot(sim_, addrs_, i, maxraftstate_));
  }
  void shutdown_server(int i) {
    sim_->kill(addrs_[i]);
    servers_[i] = nullptr;
  }

  std::optional<int> leader() const {  // tester.rs:172-182
    for (int i = 0; i < n_; i++)
      if (servers_[i] && servers_[i]->is_leader()) return i;
    return std::nullopt;
  }

  // ---- topology (tester.rs:88-124)
  void connect(int i, const std::vector<int>& to) {
    for (int j : to) sim_->connect2(addrs_[i], addrs_[j]);
  }
  void disconnect(int i, const std::vector<int>& from) {
    for (int j : from) sim_->disconnect2(addrs_[i], addrs_[j]);
  }
  void connect_all() {
    for (int i = 0; i < n_; i++) connect(i, all());
  }
  void partition(const std::vector<int>& p1, const std::vector<int>& p2) {
    for (int i : p1) {
      disconnect(i, p2);
      connect(i, p1);
    }
    for (int i : p2) {
      disconnect(i, p1);
      connect(i, p2);
    }
  }
  // split with the current leader in the minority (tester.rs:184-191)
  std::pair<std::vector<int>, std::vector<int>> make_partition() const {
    int l = leader().value_or(0);
    std::vector<int> p1;
    for (int i = 0; i < n_; i++)
      if (i != l) p1.push_back(i);
    std::vector<int> p2(p1.begin() + n_ / 2 + 1, p1.end());
    p1.resize(n_ / 2 + 1);
    p2.push_back(l);
    return {p1, p2};
  }

  // ---- metrics (tester.rs:66-85)
  size_t log_size() const {
    size_t m = 0;
    for (auto a : addrs_) m = std::max(m, sim_->fs_size(a, "state"));
    return m;
  }
  size_t snapshot_size() const {
    size_t m = 0;
    for (auto a : addrs_) m = std::max(m, sim_->fs_size(a, "snapshot"));
    return m;
  }

  // ---- clerks (tester.rs:129-150, 214-271)
  class Clerk {
   public:
    Clerk(Sim* sim, Addr addr, std::shared_ptr<KvClerk> ck, uint64_t id,
          std::shared_ptr<uint64_t> ops)
        : sim_(sim), addr_(addr), ck_(std::move(ck)), id_(id),
          ops_(std::move(ops)) {}

    uint64_t id() const { return id_; }

    // every op runs as the clerk's node so the sim routes/partitions it
    // by the clerk's address (tester.rs:235-263)
    Task<void> put(std::string k, std::string v) {
      ++*ops_;
      co_await sim_->spawn(addr_, ck_->put(std::move(k), std::move(v)));
    }
    Task<void> append(std::string k, std::string v) {
      ++*ops_;
      co_await sim_->spawn(addr_, ck_->append(std::move(k), std::move(v)));
    }
    Task<std::string> get(std::string k) {
      ++*ops_;
      co_return co_await sim_->spawn(addr_, ck_->get(std::move(k)));
    }
    Task<void> check(std::string k, std::string expected) {  // tester.rs:266-271
      auto v = co_await sim_->spawn(addr_, ck_->get(k));
      if (v != expected) {
        std::fprintf(stderr, "get(%s) check failed: got %.120s want %.120s\n",
                     k.c_str(), v.c_str(), expected.c_str());
        std::abort();
      }
    }

   private:
    Sim* sim_;
    Addr addr_;
    std::shared_ptr<KvClerk> ck_;
    uint64_t id_;
    std::shared_ptr<uint64_t> ops_;
  };

  Clerk make_client(const std::vector<int>& to) {
    uint64_t id = next_client_++;
    connect_client(id, to);
    return Clerk(sim_, clerk_addr(id),
                 std::make_shared<KvClerk>(sim_, addrs_, id), id, ops_);
  }

  void connect_client(uint64_t id, const std::vector<int>& to) {
    Addr a = clerk_addr(id);
    sim_->connect(a);
    for (int i = 0; i < n_; i++) sim_->disconnect2(a, addrs_[i]);
    for (int i : to) sim_->connect2(a, addrs_[i]);
  }

  static Addr clerk_addr(uint64_t id) { return make_addr(0, 0, 2, id + 1); }

  void end() const {  // tester.rs:197-211
    std::printf("  ... elapsed %.2fs(virt) peers %d rpcs %llu ops %llu\n",
                (sim_->now() - start_time_) / 1e9, n_,
                (unsigned long long)(sim_->msg_count() / 2),
                (unsigned long long)*ops_);
  }

 private:
  Sim* sim_;
  int n_;
  std::optional<size_t> maxraftstate_;
  uint64_t start_time_;
  std::vector<Addr> addrs_;
  std::vector<std::shared_ptr<KvServer>> servers_;
  uint64_t next_client_ = 0;
  std::shared_ptr<uint64_t> ops_ = std::make_shared<uint64_t>(0);
};

}  // namespace kvraft
