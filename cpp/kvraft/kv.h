// kv — the Lab 3 key/value service on the generic RSM layer (SURVEY.md §2 C7):
//   Op::{Get{key}, Put{key,value}, Append{key,value}}
//                               (/root/reference/src/kvraft/msg.rs:3-8)
//   Kv state machine, Output = String  (/root/reference/src/kvraft/server.rs:73-87)
//   Clerk verbs get/put/append; get returns "" for a missing key
//                               (/root/reference/src/kvraft/client.rs:16-29)
#pragma once

#include "rsm.h"

namespace kvraft {

struct Op {
  enum class Kind : uint8_t { Get, Put, Append } kind = Kind::Get;
  std::string key;
  std::string value;
  // non-aggregate on purpose — see the gcc-12 note in rsm.h
  Op() = default;
  Op(Kind k, std::string key_, std::string value_)
      : kind(k), key(std::move(key_)), value(std::move(value_)) {}
};

struct Kv {
  using Command = Op;
  using Output = std::string;

  std::map<std::string, std::string> data;  // std::map: deterministic iteration

  Output apply(const Op& op) {
    switch (op.kind) {
      case Op::Kind::Get: {
        auto it = data.find(op.key);
        return it == data.end() ? std::string() : it->second;
      }
      case Op::Kind::Put:
        data[op.key] = op.value;
        return {};
      case Op::Kind::Append:
        data[op.key] += op.value;
        return {};
    }
    return {};
  }

  static void enc_cmd(Enc& e, const Op& op) {
    e.u64(uint64_t(op.kind));
    e.str(op.key);
    e.str(op.value);
  }
  static Op dec_cmd(Dec& d) {
    Op op;
    op.kind = Op::Kind(d.u64());
    op.key = d.str();
    op.value = d.str();
    return op;
  }

  static void enc_out(Enc& e, const std::string& s) { e.str(s); }
  static std::string dec_out(Dec& d) { return d.str(); }

  void save(Enc& e) const {
    e.u64(data.size());
    for (auto& [k, v] : data) {
      e.str(k);
      e.str(v);
    }
  }
  void load(Dec& d) {
    data.clear();
    uint64_t n = d.u64();
    for (uint64_t i = 0; i < n; i++) {
      auto k = d.str();
      data[k] = d.str();
    }
  }
};

using KvServer = RsmServer<Kv>;

// client.rs:5-30
class KvClerk {
 public:
  KvClerk(Sim* sim, std::vector<Addr> servers, uint64_t id)
      : core_(sim, std::move(servers), id) {}

  Task<std::string> get(std::string key) {
    return core_.call(Op{Op::Kind::Get, std::move(key), {}});
  }
  Task<std::string> put(std::string key, std::string value) {
    return core_.call(Op{Op::Kind::Put, std::move(key), std::move(value)});
  }
  Task<std::string> append(std::string key, std::string value) {
    return core_.call(Op{Op::Kind::Append, std::move(key), std::move(value)});
  }
  uint64_t id() const { return core_.id(); }

 private:
  ClerkCore<Kv> core_;
};

}  // namespace kvraft
