// rsm — the generic replicated-state-machine layer (L2 of SURVEY.md §1):
// a service-agnostic server that funnels client commands through raft-core,
// plus the generic retrying client. This implements in full what the
// reference scaffolds as todo!() stubs:
//
//   trait State { Command; Output; apply }   (/root/reference/src/kvraft/server.rs:12-16)
//   Server<S: State>::new(servers, me, max_raft_state)  (server.rs:31-46)
//   Server::apply — submit via raft, await commit, dedup retries
//                                            (server.rs:68-70, todo!())
//   ClerkCore<Req, Rsp>::call — cycle servers, 500ms timeout, handle
//     NotLeader{hint}/Timeout/Failed, retry forever  (client.rs:32-63)
//   Error::{NotLeader{hint}, Timeout, Failed}  (/root/reference/src/kvraft/msg.rs:10-18)
//
// Design notes (not a port):
//  * Exactly-once semantics: every request carries (client id, seq). The
//    server keeps a per-client table of the last applied seq + its output;
//    a retried command that already committed returns the cached output
//    instead of re-applying. The table is part of the snapshot, and is
//    rebuilt by log replay after a restart without snapshots.
//  * The RPC handler coroutine submits to raft and then polls virtual time
//    until the entry applies or the term moves on; polling is free in a
//    discrete-event simulator.
//  * Snapshot trigger: after each apply, if the on-disk raft "state" file
//    exceeds max_raft_state, the server hands raft a snapshot (state + dup
//    table). The tester asserts log ≤ 2×max_raft_state
//    (/root/reference/src/kvraft/tests.rs:207-216).
#pragma once

#include <map>
#include <memory>
#include <optional>
#include <string>
#include <vector>

#include "../raftcore/raft.h"

namespace kvraft {

using raftcore::ApplyMsg;
using raftcore::Bytes;
using raftcore::Dec;
using raftcore::Enc;
using raftcore::Raft;
using simcore::Addr;
using simcore::Channel;
using simcore::MSEC;
using simcore::SEC;
using simcore::Sim;
using simcore::Task;
using simcore::TaskRef;

// msg.rs:10-18 — Ok carries the output; the other three drive clerk retry.
enum class Code : uint8_t { Ok, NotLeader, Failed };

// NOTE: every message type that carries strings (or anything else
// self-referential under SSO) MUST be a non-aggregate — i.e. declare a
// constructor. gcc 12's coroutine codegen bitwise-relocates aggregate
// prvalues crossing coroutine boundaries (parameters, awaiter temporaries)
// without running move ctors, which corrupts SSO strings. Vectors and PODs
// survive the relocation; strings do not. Non-aggregates take the proper
// move-construction path.

template <class Output>
struct RsmReply {
  Code code = Code::Failed;
  int hint = -1;  // NotLeader: last observed leader
  Output out{};
  RsmReply() = default;
  RsmReply(Code c, int h = -1, Output o = {})
      : code(c), hint(h), out(std::move(o)) {}
};

// Wire request: the service command tagged with clerk identity for dup
// detection (the requirement implied by server.rs:68-70's "dedup retries").
template <class S>
struct RsmRequest {
  uint64_t client = 0;
  uint64_t seq = 0;
  typename S::Command cmd{};
  using Reply = RsmReply<typename S::Output>;
  RsmRequest() = default;
  RsmRequest(uint64_t c, uint64_t s, typename S::Command cmd_)
      : client(c), seq(s), cmd(std::move(cmd_)) {}
};

// Shared propose-wait idiom: poll virtual time until the caller's apply
// cursor reaches `index`, or leadership/term moved on (returns false — the
// entry may have been superseded and the client must retry). `applied` is a
// reference to the server's apply-channel counter; the caller's coroutine
// frame keeps the server alive across the awaits.
inline Task<bool> wait_applied(Sim* sim, Raft& raft, const uint64_t& applied,
                               uint64_t index, uint64_t term) {
  while (applied < index) {
    if (raft.term() != term || !raft.is_leader()) co_return false;
    co_await sim->sleep(5 * MSEC);
  }
  co_return true;
}

// Shared snapshot trigger (server.rs:34's max_raft_state watermark): when the
// raft "state" file outgrows the limit, capture the service state via `save`
// and hand it to raft for log truncation.
template <class SaveFn>
void snapshot_if_oversized(Sim* sim, Addr addr,
                           const std::optional<size_t>& max_raft_state,
                           Raft& raft, uint64_t index, SaveFn&& save) {
  if (!max_raft_state) return;
  if (sim->fs_size(addr, "state") < *max_raft_state) return;
  Enc e;
  save(e);
  raft.snapshot(index, std::move(e.out));
}

// Server<S: State> (server.rs:18-71). S must provide:
//   using Command / using Output            (copyable values)
//   Output apply(const Command&)
//   static void enc_cmd(Enc&, const Command&) / static Command dec_cmd(Dec&)
//   static void enc_out(Enc&, const Output&) / static Output dec_out(Dec&)
//   void save(Enc&) const / void load(Dec&)  (snapshot payload)
template <class S>
class RsmServer : public std::enable_shared_from_this<RsmServer<S>> {
 public:
  using Output = typename S::Output;
  using Reply = RsmReply<Output>;

  // Must be spawned on servers[me]'s address (the reference boots via
  // local_handle(addr).spawn(KvServer::new), kvraft/tester.rs:164-168).
  static Task<std::shared_ptr<RsmServer>> boot(Sim* sim,
                                               std::vector<Addr> servers,
                                               size_t me,
                                               std::optional<size_t> max_raft_state) {
    return boot_as<RsmServer>(sim, std::move(servers), me, max_raft_state);
  }

  // Boot a subclass (must add no state; e.g. ShardCtrler registers one extra
  // RPC handler on top) through the SAME boot path — one implementation of
  // the raft-boot + handler + applier sequence, so it cannot diverge.
  template <class Derived>
  static Task<std::shared_ptr<Derived>> boot_as(
      Sim* sim, std::vector<Addr> servers, size_t me,
      std::optional<size_t> max_raft_state) {
    auto self =
        std::shared_ptr<Derived>(new Derived(sim, servers, me, max_raft_state));
    self->raft_ =
        co_await sim->spawn(Raft::boot(sim, servers, me, self->apply_ch_));
    sim->add_rpc_handler<RsmRequest<S>>([self](RsmRequest<S> req) {
      return handle(self, std::move(req));
    });
    sim->spawn(applier(self));
    co_return self;
  }

  uint64_t term() const { return raft_->term(); }        // server.rs:59-61
  bool is_leader() const { return raft_->is_leader(); }  // server.rs:64-66
  const S& state() const { return state_; }
  Raft& raft() { return *raft_; }

 protected:
  RsmServer(Sim* sim, std::vector<Addr> servers, size_t me,
            std::optional<size_t> mrs)
      : sim_(sim), addr_(servers[me]), max_raft_state_(mrs) {}

  // the reference's Server::apply (server.rs:68-70): submit, await, dedup
  static Task<Reply> handle(std::shared_ptr<RsmServer> self, RsmRequest<S> req) {
    Enc e;
    e.u64(req.client);
    e.u64(req.seq);
    S::enc_cmd(e, req.cmd);
    auto r = self->raft_->start(std::move(e.out));
    if (!r.ok) co_return Reply{Code::NotLeader, r.hint};
    if (!co_await wait_applied(self->sim_, *self->raft_, self->applied_,
                               r.index, r.term))
      co_return Reply{Code::Failed};
    auto it = self->dup_.find(req.client);
    if (it != self->dup_.end() && it->second.seq >= req.seq)
      co_return Reply{Code::Ok, -1, it->second.out};
    // a different entry landed at our index (leader turnover): client retries
    co_return Reply{Code::Failed};
  }

  static Task<void> applier(std::shared_ptr<RsmServer> self) {
    for (;;) {
      auto m = co_await self->apply_ch_.recv();
      if (!m) break;
      if (m->is_snapshot) {
        if (self->raft_->cond_install_snapshot(m->term, m->index, m->data)) {
          Dec d(m->data);
          self->load_snapshot(d);
          self->applied_ = m->index;
        }
      } else {
        Dec d(m->data);
        uint64_t client = d.u64();
        uint64_t seq = d.u64();
        auto cmd = S::dec_cmd(d);
        auto& rec = self->dup_[client];
        // Exactly-once contract: a clerk has ONE outstanding op and bumps seq
        // only after the previous op returned Ok (i.e. committed), so seqs
        // commit in order with no gaps. Entries with seq <= rec.seq are late
        // duplicates of already-applied ops: skip, keep the cached output.
        if (seq > rec.seq + 1) {
          std::fprintf(stderr,
                       "rsm: client %llu seq gap (%llu after %llu) — "
                       "concurrent use of one clerk?\n",
                       (unsigned long long)client, (unsigned long long)seq,
                       (unsigned long long)rec.seq);
          std::abort();
        }
        if (seq > rec.seq) {  // first time: apply; else serve cached output
          rec.out = self->state_.apply(cmd);
          rec.seq = seq;
        }
        self->applied_ = m->index;
        self->maybe_snapshot(m->index);
      }
    }
  }

  void maybe_snapshot(uint64_t index) {
    snapshot_if_oversized(sim_, addr_, max_raft_state_, *raft_, index,
                          [this](Enc& e) { save_snapshot(e); });
  }

  void save_snapshot(Enc& e) const {
    e.u64(dup_.size());
    for (auto& [client, rec] : dup_) {  // std::map: deterministic order
      e.u64(client);
      e.u64(rec.seq);
      S::enc_out(e, rec.out);
    }
    state_.save(e);
  }
  void load_snapshot(Dec& d) {
    dup_.clear();
    uint64_t n = d.u64();
    for (uint64_t i = 0; i < n; i++) {
      uint64_t client = d.u64();
      auto& rec = dup_[client];
      rec.seq = d.u64();
      rec.out = S::dec_out(d);
    }
    state_ = S{};
    state_.load(d);
  }

  struct DupRec {
    uint64_t seq = 0;
    Output out{};
  };

  Sim* sim_;
  Addr addr_;
  std::optional<size_t> max_raft_state_;
  Channel<ApplyMsg> apply_ch_;
  std::shared_ptr<Raft> raft_;
  S state_{};
  std::map<uint64_t, DupRec> dup_;  // client -> last applied (seq, output)
  uint64_t applied_ = 0;
};

// ClerkCore<Req, Rsp> (client.rs:32-63): cycle over servers with a 500 ms
// per-call timeout, follow NotLeader hints, retry forever.
// CONTRACT: one outstanding call() at a time per ClerkCore — seq advances
// only after the previous op committed; the server's dup table relies on
// gap-free per-client seqs (asserted in RsmServer::applier).
template <class S>
class ClerkCore {
 public:
  ClerkCore(Sim* sim, std::vector<Addr> servers, uint64_t client_id)
      : sim_(sim), servers_(std::move(servers)), id_(client_id) {}

  Task<typename S::Output> call(typename S::Command cmd) {
    uint64_t seq = ++seq_;
    size_t i = leader_;
    for (;;) {
      auto reply = co_await sim_->call_timeout(
          servers_[i], RsmRequest<S>{id_, seq, cmd}, 500 * MSEC);  // client.rs:56
      if (reply && reply->code == Code::Ok) {
        leader_ = i;
        co_return reply->out;
      }
      if (reply && reply->code == Code::NotLeader && reply->hint >= 0 &&
          size_t(reply->hint) < servers_.size() && size_t(reply->hint) != i) {
        i = size_t(reply->hint);
      } else {
        i = (i + 1) % servers_.size();
      }
    }
  }

  uint64_t id() const { return id_; }
  const std::vector<Addr>& servers() const { return servers_; }

 private:
  Sim* sim_;
  std::vector<Addr> servers_;
  uint64_t id_;
  uint64_t seq_ = 0;
  size_t leader_ = 0;
};

}  // namespace kvraft
