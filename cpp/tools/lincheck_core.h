// Core of madtpu_lincheck (history parsing for the Wing-Gong checker),
// shared by the CLI binary (lincheck_main.cpp) and the in-process C API
// (capi.cpp / libmadtpu.so -> madraft_tpu/simcore.py). History format: see
// lincheck_main.cpp.
#pragma once

#include <cstdio>
#include <sstream>
#include <string>
#include <vector>

#include "../kvraft/linearize.h"

namespace madtpu_lincheck {

// -> 1 linearizable, 0 not, -1 parse error
inline int check_history_text(const std::string& text) {
  std::vector<kvraft::HistOp> hist;
  std::istringstream f(text);
  std::string line;
  while (std::getline(f, line)) {
    if (line.empty() || line[0] == '#') continue;
    std::istringstream ss(line);
    std::string tag, kind, key, value;
    unsigned long long invoke, ret;
    ss >> tag >> invoke >> ret >> kind >> key;
    if (!ss || tag != "op") return -1;
    ss >> value;  // may be absent: an empty Get output is legal
    kvraft::HistOp h;
    h.invoke = invoke;
    h.ret = ret;
    h.key = key;
    if (kind == "get") {
      h.kind = kvraft::Op::Kind::Get;
      h.output = value;
    } else if (kind == "put") {
      h.kind = kvraft::Op::Kind::Put;
      h.input = value;
    } else {
      h.kind = kvraft::Op::Kind::Append;
      h.input = value;
    }
    hist.push_back(std::move(h));
  }
  return kvraft::check_linearizable_kv(hist) ? 1 : 0;
}

}  // namespace madtpu_lincheck
