// Core of madtpu_replay (raw-raft differential replay), shared by the CLI
// binary (replay_main.cpp) and the in-process C API (capi.cpp /
// libmadtpu.so -> madraft_tpu/simcore.py). See replay_main.cpp for the
// schedule format and the bridge contract.
#pragma once

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "../raftcore/raft.h"
#include "env_guard.h"

namespace madtpu_replay {

using namespace raftcore;
using simcore::Addr;
using simcore::make_addr;
using simcore::MSEC;
using simcore::Sim;

struct Event {
  uint64_t tick;
  bool is_alive;                  // else adj
  uint64_t alive_mask;
  std::vector<uint64_t> adj_rows;
};

struct Schedule {
  int nodes = 0;
  uint64_t ms_per_tick = 10;
  uint64_t ticks = 0;
  int majority_override = 0;
  std::string bug;                // planted bug name ("" = correct algorithm;
  //                                 raftcore raft.cpp bug(), config.py RAFT_BUGS)
  uint64_t seed = 0;
  bool trace = false;             // per-tick state export ("trace 1" line):
  //                                 the report gains a "trace" object with
  //                                 alive/leader masks and per-node
  //                                 term/commit/len arrays, one row per tick
  //                                 — the C++ half of the bridge's
  //                                 divergence localization (bridge.py)
  std::vector<Event> events;      // sorted by tick
};

inline bool parse_schedule(FILE* f, Schedule* out) {
  char line[4096];
  while (std::fgets(line, sizeof line, f)) {
    if (line[0] == '#' || line[0] == '\n') continue;
    char kw[64];
    if (std::sscanf(line, "%63s", kw) != 1) continue;
    if (!std::strcmp(kw, "nodes")) {
      std::sscanf(line, "%*s %d", &out->nodes);
    } else if (!std::strcmp(kw, "ms_per_tick")) {
      std::sscanf(line, "%*s %" SCNu64, &out->ms_per_tick);
    } else if (!std::strcmp(kw, "ticks")) {
      std::sscanf(line, "%*s %" SCNu64, &out->ticks);
    } else if (!std::strcmp(kw, "majority_override")) {
      std::sscanf(line, "%*s %d", &out->majority_override);
    } else if (!std::strcmp(kw, "bug")) {
      char name[64] = {0};
      if (std::sscanf(line, "%*s %63s", name) == 1) out->bug = name;
      if (!madtpu_tools::is_known_raft_bug(out->bug)) return false;
    } else if (!std::strcmp(kw, "seed")) {
      std::sscanf(line, "%*s %" SCNu64, &out->seed);
    } else if (!std::strcmp(kw, "trace")) {
      int v = 0;
      std::sscanf(line, "%*s %d", &v);
      out->trace = v != 0;
    } else if (!std::strcmp(kw, "ev")) {
      Event ev{};
      char kind[32];
      int consumed = 0;
      if (std::sscanf(line, "%*s %" SCNu64 " %31s %n", &ev.tick, kind,
                      &consumed) < 2)
        continue;
      const char* rest = line + consumed;
      if (!std::strcmp(kind, "alive")) {
        ev.is_alive = true;
        ev.alive_mask = std::strtoull(rest, nullptr, 16);
      } else {
        ev.is_alive = false;
        char* end = nullptr;
        const char* p = rest;
        for (int i = 0; i < out->nodes; i++) {
          ev.adj_rows.push_back(std::strtoull(p, &end, 16));
          p = end;
        }
      }
      out->events.push_back(std::move(ev));
    }
  }
  if (out->nodes <= 0 || out->ticks == 0) return false;
  // an adj event parsed before the `nodes` line has too few rows; reject
  // rather than index out of bounds at replay time
  for (const auto& ev : out->events)
    if (!ev.is_alive && ev.adj_rows.size() != (size_t)out->nodes) return false;
  return true;
}

// Replay harness: like RaftTester but violations are REPORTED, not aborted —
// the bridge's whole point is to observe them.
struct Replay {
  Sim* sim;
  int n;
  std::vector<Addr> addrs;
  std::vector<std::shared_ptr<Raft>> rafts;
  std::vector<std::vector<uint64_t>> storage;  // applied values, 1-based
  bool dual_leader = false;
  bool commit_mismatch = false;
  bool apply_disorder = false;
  uint64_t first_violation_ms = 0;
  uint64_t max_applied = 0;
  // per-tick flight-recorder samples (Schedule::trace; one row per tick)
  std::vector<uint64_t> tr_alive, tr_leader;          // node bitmasks
  std::vector<std::vector<uint64_t>> tr_term, tr_commit, tr_len;

  Replay(Sim* s, int n_) : sim(s), n(n_) {
    for (int i = 0; i < n; i++) addrs.push_back(make_addr(0, 0, 1, i + 1));
    rafts.resize(n);
    storage.resize(n);
  }

  void flag(bool* which) {
    if (!dual_leader && !commit_mismatch && !apply_disorder)
      first_violation_ms = sim->now() / MSEC;
    *which = true;
  }

  void push_and_check(int i, uint64_t index, uint64_t v) {
    for (int j = 0; j < n; j++)
      if (j != i && storage[j].size() >= index && storage[j][index - 1] != v)
        flag(&commit_mismatch);
    if (index == storage[i].size() + 1) {
      storage[i].push_back(v);
    } else if (index <= storage[i].size()) {
      if (storage[i][index - 1] != v) flag(&commit_mismatch);
    } else {
      flag(&apply_disorder);
    }
    max_applied = std::max<uint64_t>(max_applied, storage[i].size());
  }

  static simcore::Task<void> applier(Replay* r, int i,
                                     simcore::Channel<ApplyMsg> ch) {
    for (;;) {
      auto m = co_await ch.recv();
      if (!m) break;
      if (m->is_snapshot) {
        if (r->rafts[i] &&
            r->rafts[i]->cond_install_snapshot(m->term, m->index, m->data)) {
          Dec d(m->data);
          uint64_t len = d.u64();
          r->storage[i].clear();
          for (uint64_t k = 0; k < len; k++) r->storage[i].push_back(d.u64());
        }
      } else {
        r->push_and_check(i, m->index, dec_u64(m->data));
      }
    }
  }

  simcore::Task<void> start1(int i) {
    sim->kill(addrs[i]);
    rafts[i] = nullptr;
    simcore::Channel<ApplyMsg> ch;
    rafts[i] = co_await sim->spawn(addrs[i], Raft::boot(sim, addrs, i, ch));
    sim->spawn(addrs[i], applier(this, i, ch));
  }

  void crash1(int i) {
    sim->kill(addrs[i]);
    rafts[i] = nullptr;
  }
};

inline simcore::Task<void> client_task(Replay* r, uint64_t end_ns) {
  uint64_t cmd = 1;
  while (r->sim->now() < end_ns) {
    for (int i = 0; i < r->n; i++)
      if (r->rafts[i] && r->rafts[i]->is_leader())
        r->rafts[i]->start(enc_u64(cmd++));
    co_await r->sim->sleep(20 * MSEC);
  }
}

inline simcore::Task<void> leader_poll_task(Replay* r, uint64_t end_ns) {
  while (r->sim->now() < end_ns) {
    std::map<uint64_t, int> leaders;
    for (int i = 0; i < r->n; i++)
      if (r->rafts[i] && r->rafts[i]->is_leader())
        if (++leaders[r->rafts[i]->term()] > 1) r->flag(&r->dual_leader);
    co_await r->sim->sleep(5 * MSEC);
  }
}

// Flight-recorder sampler (Schedule::trace): one state snapshot per tick,
// taken 1ns PAST the tick boundary so the sample deterministically follows
// the driver's fault events scheduled AT the boundary — C++ sample k then
// corresponds to the TPU trace's post-tick state at tick k, and the alive
// masks must match the schedule exactly (the bridge's strongest
// cross-backend divergence signal).
inline simcore::Task<void> trace_task(Replay* r, const Schedule* sch) {
  for (uint64_t k = 1; k <= sch->ticks; k++) {
    uint64_t at = k * sch->ms_per_tick * MSEC + 1;
    if (at > r->sim->now()) co_await r->sim->sleep(at - r->sim->now());
    uint64_t am = 0, lm = 0;
    std::vector<uint64_t> tm(r->n, 0), cm(r->n, 0), ln(r->n, 0);
    for (int i = 0; i < r->n; i++) {
      if (!r->rafts[i]) continue;
      am |= 1ull << i;
      if (r->rafts[i]->is_leader()) lm |= 1ull << i;
      tm[i] = r->rafts[i]->term();
      cm[i] = r->rafts[i]->commit_index();
      ln[i] = r->rafts[i]->last_index();
    }
    r->tr_alive.push_back(am);
    r->tr_leader.push_back(lm);
    r->tr_term.push_back(std::move(tm));
    r->tr_commit.push_back(std::move(cm));
    r->tr_len.push_back(std::move(ln));
  }
}

inline simcore::Task<void> replay_driver(Sim* sim, Replay* r,
                                         const Schedule* sch) {
  for (int i = 0; i < r->n; i++) {
    co_await sim->spawn(r->start1(i));
    sim->connect(r->addrs[i]);
  }
  uint64_t end_ns = sch->ticks * sch->ms_per_tick * MSEC;
  sim->spawn(Addr(0), client_task(r, end_ns));       // TaskRef is non-owning
  sim->spawn(Addr(0), leader_poll_task(r, end_ns));  // (drop = detach)
  if (sch->trace) sim->spawn(Addr(0), trace_task(r, sch));

  uint64_t alive = ~0ull;
  for (const auto& ev : sch->events) {
    uint64_t at = ev.tick * sch->ms_per_tick * MSEC;
    if (at > sim->now()) co_await sim->sleep(at - sim->now());
    if (ev.is_alive) {
      for (int i = 0; i < r->n; i++) {
        bool was = (alive >> i) & 1, now = (ev.alive_mask >> i) & 1;
        if (was && !now) r->crash1(i);
        if (!was && now) co_await sim->spawn(r->start1(i));
      }
      alive = ev.alive_mask;
    } else {
      for (int i = 0; i < r->n; i++)
        for (int j = i + 1; j < r->n; j++) {
          bool up = (ev.adj_rows[i] >> j) & 1;
          if (up)
            sim->connect2(r->addrs[i], r->addrs[j]);
          else
            sim->disconnect2(r->addrs[i], r->addrs[j]);
        }
    }
  }
  // when tracing, run 2ns past the horizon so the sampler's final snapshot
  // (at end_ns + 1) deterministically lands before the sim stops. The
  // window is nanoseconds, not a tick, so no raft traffic or applier work
  // can fire inside it — the traced run observes exactly the same
  // simulation the untraced (classified) run did.
  uint64_t drain_ns = end_ns + (sch->trace ? 2 : 0);
  if (drain_ns > sim->now()) co_await sim->sleep(drain_ns - sim->now());
}

// Run a schedule; returns the one-line JSON report ("" = sim deadlock).
// The majority override is applied via env so raftcore's quorum() (which
// reads it per call, uncached) sees it — and restored afterwards so
// in-process callers can interleave overridden and clean replays. Callers
// serialize (capi.cpp holds a mutex); env mutation is not thread-safe.
inline std::string run_schedule(const Schedule& sch) {
  char buf[16] = {0};
  if (sch.majority_override > 0)
    std::snprintf(buf, sizeof buf, "%d", sch.majority_override);
  madtpu_tools::EnvGuard guard(
      "MADTPU_MAJORITY_OVERRIDE",
      sch.majority_override > 0 ? buf : nullptr);
  madtpu_tools::EnvGuard bug_guard(
      "MADTPU_BUG", !sch.bug.empty() ? sch.bug.c_str() : nullptr);
  Sim sim(sch.seed);
  Replay r(&sim, sch.nodes);
  if (!sim.run(replay_driver(&sim, &r, &sch))) return "";
  char out[512];
  std::snprintf(
      out, sizeof out,
      "{\"dual_leader\": %d, \"commit_mismatch\": %d, \"apply_disorder\": %d, "
      "\"first_violation_ms\": %" PRIu64 ", \"max_applied\": %" PRIu64
      ", \"rpcs\": %" PRIu64,
      (int)r.dual_leader, (int)r.commit_mismatch, (int)r.apply_disorder,
      r.first_violation_ms, r.max_applied, sim.msg_count() / 2);
  std::string report(out);
  if (sch.trace) {
    auto masks = [](const std::vector<uint64_t>& v) {
      std::string s = "[";
      for (size_t i = 0; i < v.size(); i++) {
        if (i) s += ",";
        s += std::to_string(v[i]);
      }
      return s + "]";
    };
    auto rows = [&](const std::vector<std::vector<uint64_t>>& m) {
      std::string s = "[";
      for (size_t i = 0; i < m.size(); i++) {
        if (i) s += ",";
        s += masks(m[i]);
      }
      return s + "]";
    };
    report += ", \"trace\": {\"ms_per_tick\": ";
    report += std::to_string(sch.ms_per_tick);
    report += ", \"alive\": " + masks(r.tr_alive);
    report += ", \"leader\": " + masks(r.tr_leader);
    report += ", \"term\": " + rows(r.tr_term);
    report += ", \"commit\": " + rows(r.tr_commit);
    report += ", \"len\": " + rows(r.tr_len);
    report += "}";
  }
  report += "}";
  return report;
}

}  // namespace madtpu_replay
