// madtpu_ctrler_replay — CLI front of the Lab-4A differential bridge.
// See ctrler_replay_core.h for the schedule format and checker semantics.
// Output: one JSON line; exit 0 if the replay ran, 2 on a bad schedule.
#include "ctrler_replay_core.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <schedule-file>\n", argv[0]);
    return 2;
  }
  FILE* f = std::fopen(argv[1], "r");
  madtpu_ctrler_replay::Schedule sch;
  bool ok = f && madtpu_ctrler_replay::parse_schedule(f, &sch);
  if (f) std::fclose(f);
  if (!ok) {
    std::fprintf(stderr, "bad schedule file: %s\n", argv[1]);
    return 2;
  }
  std::puts(madtpu_ctrler_replay::run_schedule(sch).c_str());
  return 0;
}
