// madtpu_replay — the simcore end of the TPU<->C++ differential bridge.
//
// The batched TPU fuzzer (madraft_tpu/tpusim) reports a violating cluster as
// (seed, cluster_id); the Python side (madraft_tpu/bridge.py) re-runs that one
// cluster on host and exports its FAULT SCHEDULE — the per-tick alive bitmask
// and adjacency matrix — as a small text file. This binary replays that
// schedule against the C++ raft-core on simcore and reports which safety
// violation classes its online checkers observed. Schedules, not PRNG streams,
// are the interchange (SURVEY.md §7 "determinism across backends"); the
// reference's analogous contract is seed replay
// (/root/reference/README.md:42-55).
//
// Schedule format (line-based; '#' comments):
//   nodes <n>
//   ms_per_tick <ms>
//   ticks <t_end>
//   majority_override <q>      # 0 = correct quorum
//   bug <name>                 # planted bug (config.py RAFT_BUGS), optional
//   seed <u64>                 # simcore PRNG seed (timeout draws etc.)
//   ev <tick> alive <hexmask>  # bit i = node i alive from this tick on
//   ev <tick> adj <hexrow0> <hexrow1> ...  # row i bit j = link i<->j usable
//
// Output: one JSON line {"dual_leader":0|1,"commit_mismatch":0|1,...};
// exit 0 if the replay ran (violations are data, not errors).
// Core logic lives in replay_core.h, shared with the in-process C API
// (capi.cpp -> libmadtpu.so -> madraft_tpu/simcore.py).
#include "replay_core.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <schedule-file>\n", argv[0]);
    return 2;
  }
  FILE* f = std::fopen(argv[1], "r");
  madtpu_replay::Schedule sch;
  bool ok = f && madtpu_replay::parse_schedule(f, &sch);
  if (f) std::fclose(f);
  if (!ok) {
    std::fprintf(stderr, "bad schedule file: %s\n", argv[1]);
    return 2;
  }
  std::string report = madtpu_replay::run_schedule(sch);
  if (report.empty()) {
    std::fprintf(stderr, "sim deadlocked\n");
    return 2;
  }
  std::printf("%s\n", report.c_str());
  return 0;
}
