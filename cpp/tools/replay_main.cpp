// madtpu_replay — the simcore end of the TPU<->C++ differential bridge.
//
// The batched TPU fuzzer (madraft_tpu/tpusim) reports a violating cluster as
// (seed, cluster_id); the Python side (madraft_tpu/bridge.py) re-runs that one
// cluster on host and exports its FAULT SCHEDULE — the per-tick alive bitmask
// and adjacency matrix — as a small text file. This binary replays that
// schedule against the C++ raft-core on simcore and reports which safety
// violation classes its online checkers observed. Schedules, not PRNG streams,
// are the interchange (SURVEY.md §7 "determinism across backends"); the
// reference's analogous contract is seed replay
// (/root/reference/README.md:42-55).
//
// Schedule format (line-based; '#' comments):
//   nodes <n>
//   ms_per_tick <ms>
//   ticks <t_end>
//   majority_override <q>      # 0 = correct quorum
//   seed <u64>                 # simcore PRNG seed (timeout draws etc.)
//   ev <tick> alive <hexmask>  # bit i = node i alive from this tick on
//   ev <tick> adj <hexrow0> <hexrow1> ...  # row i bit j = link i<->j usable
//
// Output: one JSON line {"dual_leader":0|1,"commit_mismatch":0|1,...};
// exit 0 if the replay ran (violations are data, not errors).
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "../raftcore/raft.h"

using namespace raftcore;
using simcore::Addr;
using simcore::make_addr;
using simcore::MSEC;
using simcore::Sim;

namespace {

struct Event {
  uint64_t tick;
  bool is_alive;                  // else adj
  uint64_t alive_mask;
  std::vector<uint64_t> adj_rows;
};

struct Schedule {
  int nodes = 0;
  uint64_t ms_per_tick = 10;
  uint64_t ticks = 0;
  int majority_override = 0;
  uint64_t seed = 0;
  std::vector<Event> events;      // sorted by tick
};

bool parse_schedule(const char* path, Schedule* out) {
  FILE* f = std::fopen(path, "r");
  if (!f) return false;
  char line[4096];
  while (std::fgets(line, sizeof line, f)) {
    if (line[0] == '#' || line[0] == '\n') continue;
    char kw[64];
    if (std::sscanf(line, "%63s", kw) != 1) continue;
    if (!std::strcmp(kw, "nodes")) {
      std::sscanf(line, "%*s %d", &out->nodes);
    } else if (!std::strcmp(kw, "ms_per_tick")) {
      std::sscanf(line, "%*s %" SCNu64, &out->ms_per_tick);
    } else if (!std::strcmp(kw, "ticks")) {
      std::sscanf(line, "%*s %" SCNu64, &out->ticks);
    } else if (!std::strcmp(kw, "majority_override")) {
      std::sscanf(line, "%*s %d", &out->majority_override);
    } else if (!std::strcmp(kw, "seed")) {
      std::sscanf(line, "%*s %" SCNu64, &out->seed);
    } else if (!std::strcmp(kw, "ev")) {
      Event ev{};
      char kind[32];
      int consumed = 0;
      if (std::sscanf(line, "%*s %" SCNu64 " %31s %n", &ev.tick, kind,
                      &consumed) < 2)
        continue;
      const char* rest = line + consumed;
      if (!std::strcmp(kind, "alive")) {
        ev.is_alive = true;
        ev.alive_mask = std::strtoull(rest, nullptr, 16);
      } else {
        ev.is_alive = false;
        char* end = nullptr;
        const char* p = rest;
        for (int i = 0; i < out->nodes; i++) {
          ev.adj_rows.push_back(std::strtoull(p, &end, 16));
          p = end;
        }
      }
      out->events.push_back(std::move(ev));
    }
  }
  std::fclose(f);
  if (out->nodes <= 0 || out->ticks == 0) return false;
  // an adj event parsed before the `nodes` line has too few rows; reject
  // rather than index out of bounds at replay time
  for (const auto& ev : out->events)
    if (!ev.is_alive && ev.adj_rows.size() != (size_t)out->nodes) return false;
  return true;
}

// Replay harness: like RaftTester but violations are REPORTED, not aborted —
// the bridge's whole point is to observe them.
struct Replay {
  Sim* sim;
  int n;
  std::vector<Addr> addrs;
  std::vector<std::shared_ptr<Raft>> rafts;
  std::vector<std::vector<uint64_t>> storage;  // applied values, 1-based
  bool dual_leader = false;
  bool commit_mismatch = false;
  bool apply_disorder = false;
  uint64_t first_violation_ms = 0;
  uint64_t max_applied = 0;

  Replay(Sim* s, int n_) : sim(s), n(n_) {
    for (int i = 0; i < n; i++) addrs.push_back(make_addr(0, 0, 1, i + 1));
    rafts.resize(n);
    storage.resize(n);
  }

  void flag(bool* which) {
    if (!dual_leader && !commit_mismatch && !apply_disorder)
      first_violation_ms = sim->now() / MSEC;
    *which = true;
  }

  void push_and_check(int i, uint64_t index, uint64_t v) {
    for (int j = 0; j < n; j++)
      if (j != i && storage[j].size() >= index && storage[j][index - 1] != v)
        flag(&commit_mismatch);
    if (index == storage[i].size() + 1) {
      storage[i].push_back(v);
    } else if (index <= storage[i].size()) {
      if (storage[i][index - 1] != v) flag(&commit_mismatch);
    } else {
      flag(&apply_disorder);
    }
    max_applied = std::max<uint64_t>(max_applied, storage[i].size());
  }

  static Task<void> applier(Replay* r, int i, Channel<ApplyMsg> ch) {
    for (;;) {
      auto m = co_await ch.recv();
      if (!m) break;
      if (m->is_snapshot) {
        if (r->rafts[i] &&
            r->rafts[i]->cond_install_snapshot(m->term, m->index, m->data)) {
          Dec d(m->data);
          uint64_t len = d.u64();
          r->storage[i].clear();
          for (uint64_t k = 0; k < len; k++) r->storage[i].push_back(d.u64());
        }
      } else {
        r->push_and_check(i, m->index, dec_u64(m->data));
      }
    }
  }

  Task<void> start1(int i) {
    sim->kill(addrs[i]);
    rafts[i] = nullptr;
    Channel<ApplyMsg> ch;
    rafts[i] = co_await sim->spawn(addrs[i], Raft::boot(sim, addrs, i, ch));
    sim->spawn(addrs[i], applier(this, i, ch));
  }

  void crash1(int i) {
    sim->kill(addrs[i]);
    rafts[i] = nullptr;
  }
};

Task<void> client_task(Replay* r, uint64_t end_ns) {
  uint64_t cmd = 1;
  while (r->sim->now() < end_ns) {
    for (int i = 0; i < r->n; i++)
      if (r->rafts[i] && r->rafts[i]->is_leader())
        r->rafts[i]->start(enc_u64(cmd++));
    co_await r->sim->sleep(20 * MSEC);
  }
}

Task<void> leader_poll_task(Replay* r, uint64_t end_ns) {
  while (r->sim->now() < end_ns) {
    std::map<uint64_t, int> leaders;
    for (int i = 0; i < r->n; i++)
      if (r->rafts[i] && r->rafts[i]->is_leader())
        if (++leaders[r->rafts[i]->term()] > 1) r->flag(&r->dual_leader);
    co_await r->sim->sleep(5 * MSEC);
  }
}

Task<void> replay_main(Sim* sim, Replay* r, const Schedule* sch) {
  for (int i = 0; i < r->n; i++) {
    co_await sim->spawn(r->start1(i));
    sim->connect(r->addrs[i]);
  }
  uint64_t end_ns = sch->ticks * sch->ms_per_tick * MSEC;
  sim->spawn(Addr(0), client_task(r, end_ns));       // TaskRef is non-owning
  sim->spawn(Addr(0), leader_poll_task(r, end_ns));  // (drop = detach)

  uint64_t alive = ~0ull;
  for (const auto& ev : sch->events) {
    uint64_t at = ev.tick * sch->ms_per_tick * MSEC;
    if (at > sim->now()) co_await sim->sleep(at - sim->now());
    if (ev.is_alive) {
      for (int i = 0; i < r->n; i++) {
        bool was = (alive >> i) & 1, now = (ev.alive_mask >> i) & 1;
        if (was && !now) r->crash1(i);
        if (!was && now) co_await sim->spawn(r->start1(i));
      }
      alive = ev.alive_mask;
    } else {
      for (int i = 0; i < r->n; i++)
        for (int j = i + 1; j < r->n; j++) {
          bool up = (ev.adj_rows[i] >> j) & 1;
          if (up)
            sim->connect2(r->addrs[i], r->addrs[j]);
          else
            sim->disconnect2(r->addrs[i], r->addrs[j]);
        }
    }
  }
  if (end_ns > sim->now()) co_await sim->sleep(end_ns - sim->now());
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <schedule-file>\n", argv[0]);
    return 2;
  }
  Schedule sch;
  if (!parse_schedule(argv[1], &sch)) {
    std::fprintf(stderr, "bad schedule file: %s\n", argv[1]);
    return 2;
  }
  if (sch.majority_override > 0) {
    char buf[16];
    std::snprintf(buf, sizeof buf, "%d", sch.majority_override);
    setenv("MADTPU_MAJORITY_OVERRIDE", buf, 1);
  }
  Sim sim(sch.seed);
  Replay r(&sim, sch.nodes);
  if (!sim.run(replay_main(&sim, &r, &sch))) {
    std::fprintf(stderr, "sim deadlocked\n");
    return 2;
  }
  std::printf(
      "{\"dual_leader\": %d, \"commit_mismatch\": %d, \"apply_disorder\": %d, "
      "\"first_violation_ms\": %" PRIu64 ", \"max_applied\": %" PRIu64
      ", \"rpcs\": %" PRIu64 "}\n",
      (int)r.dual_leader, (int)r.commit_mismatch, (int)r.apply_disorder,
      r.first_violation_ms, r.max_applied, sim.msg_count() / 2);
  return 0;
}
