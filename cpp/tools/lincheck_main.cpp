// madtpu_lincheck — run the Wing-Gong KV linearizability checker
// (cpp/kvraft/linearize.h) over a history file. The KV end of the TPU<->C++
// differential bridge: the batched fuzzer's reads-linearizability oracle
// (madraft_tpu/tpusim/kv.py) reports a violating cluster; the Python side
// exports its op history (madraft_tpu/bridge.py extract_kv_history) and this
// tool must agree on (non-)linearizability. The reference leaves these
// checks commented out (/root/reference/src/kvraft/tests.rs:386-390).
//
// History format (one op per line, '#' comments):
//   op <invoke> <ret> <get|put|append> <key> <value>
// where <value> is the Get output or the Put/Append input (no spaces).
// Output: one line "linearizable" or "NOT-linearizable"; exit 0 either way.
#include <cstdio>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "../kvraft/linearize.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <history-file>\n", argv[0]);
    return 2;
  }
  std::ifstream f(argv[1]);
  if (!f) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 2;
  }
  std::vector<kvraft::HistOp> hist;
  std::string line;
  while (std::getline(f, line)) {  // unbounded line/value length
    if (line.empty() || line[0] == '#') continue;
    std::istringstream ss(line);
    std::string tag, kind, key, value;
    unsigned long long invoke, ret;
    ss >> tag >> invoke >> ret >> kind >> key;
    if (!ss || tag != "op") {
      std::fprintf(stderr, "bad line: %s\n", line.c_str());
      return 2;
    }
    ss >> value;  // may be absent: an empty Get output is legal
    kvraft::HistOp h;
    h.invoke = invoke;
    h.ret = ret;
    h.key = key;
    if (kind == "get") {
      h.kind = kvraft::Op::Kind::Get;
      h.output = value;
    } else if (kind == "put") {
      h.kind = kvraft::Op::Kind::Put;
      h.input = value;
    } else {
      h.kind = kvraft::Op::Kind::Append;
      h.input = value;
    }
    hist.push_back(std::move(h));
  }
  bool ok = kvraft::check_linearizable_kv(hist);
  std::printf(ok ? "linearizable\n" : "NOT-linearizable\n");
  return 0;
}
