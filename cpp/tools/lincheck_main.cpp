// madtpu_lincheck — run the Wing-Gong KV linearizability checker
// (cpp/kvraft/linearize.h) over a history file. The KV end of the TPU<->C++
// differential bridge: the batched fuzzer's reads-linearizability oracle
// (madraft_tpu/tpusim/kv.py) reports a violating cluster; the Python side
// exports its op history (madraft_tpu/bridge.py extract_kv_history) and this
// tool must agree on (non-)linearizability. The reference leaves these
// checks commented out (/root/reference/src/kvraft/tests.rs:386-390).
//
// History format (one op per line, '#' comments):
//   op <invoke> <ret> <get|put|append> <key> <value>
// where <value> is the Get output or the Put/Append input (no spaces).
// Output: one line "linearizable" or "NOT-linearizable"; exit 0 either way.
// Core logic lives in lincheck_core.h, shared with the in-process C API
// (capi.cpp -> libmadtpu.so -> madraft_tpu/simcore.py).
#include <fstream>

#include "lincheck_core.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <history-file>\n", argv[0]);
    return 2;
  }
  std::ifstream f(argv[1]);
  if (!f) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 2;
  }
  std::string text((std::istreambuf_iterator<char>(f)),
                   std::istreambuf_iterator<char>());
  int r = madtpu_lincheck::check_history_text(text);
  if (r < 0) {
    std::fprintf(stderr, "bad history file: %s\n", argv[1]);
    return 2;
  }
  std::printf(r ? "linearizable\n" : "NOT-linearizable\n");
  return 0;
}
