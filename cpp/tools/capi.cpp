// libmadtpu — the in-process C API over the simcore tool suite, bound from
// Python via ctypes (madraft_tpu/simcore.py). SURVEY.md §7 architecture
// item 4 calls for Python<->C++ bindings for the bridge; pybind11 is not
// available in the build image, so this is a plain C ABI:
//
//   int madtpu_replay_run(const char* schedule, char* out, int cap);
//   int madtpu_shardkv_replay_run(const char* schedule, char* out, int cap);
//   int madtpu_ctrler_replay_run(const char* schedule, char* out, int cap);
//   int madtpu_lincheck_run(const char* history);
//
// The replay entry points take the SAME schedule text the CLI binaries
// read from files (fmemopen reuses the parsers verbatim) and write the
// SAME one-line JSON report into `out`; return = bytes written, or
// -1 parse error / -2 sim deadlock / -3 buffer too small.
// madtpu_lincheck_run returns 1 linearizable / 0 not / -1 parse error.
//
// Each call runs a fresh simcore Sim to completion on the calling thread.
// ALL entry points serialize behind one mutex: the replay knobs ride in
// process-global env vars (majority override, shardkv bug mode — set and
// RESTORED per run by an EnvGuard; the env reads in raftcore/shardkv are
// per-call, not cached, for exactly this reason), and concurrent
// setenv/getenv is undefined behavior in glibc. Concurrent Python threads
// are therefore SAFE but get no parallelism — run multiple processes for
// parallel replays.
#include <cstring>
#include <mutex>

#include "ctrler_replay_core.h"
#include "lincheck_core.h"
#include "replay_core.h"
#include "shardkv_replay_core.h"

namespace {

std::mutex g_call_mutex;

int emit(const std::string& report, char* out, int cap) {
  if (report.empty()) return -2;
  if ((int)report.size() + 1 > cap) return -3;
  std::memcpy(out, report.c_str(), report.size() + 1);
  return (int)report.size();
}

}  // namespace

extern "C" {

int madtpu_replay_run(const char* schedule, char* out, int cap) {
  std::lock_guard<std::mutex> lock(g_call_mutex);
  FILE* f = fmemopen((void*)schedule, std::strlen(schedule), "r");
  if (!f) return -1;
  madtpu_replay::Schedule sch;
  bool ok = madtpu_replay::parse_schedule(f, &sch);
  std::fclose(f);
  if (!ok) return -1;
  return emit(madtpu_replay::run_schedule(sch), out, cap);
}

int madtpu_shardkv_replay_run(const char* schedule, char* out, int cap) {
  std::lock_guard<std::mutex> lock(g_call_mutex);
  FILE* f = fmemopen((void*)schedule, std::strlen(schedule), "r");
  if (!f) return -1;
  madtpu_shardkv_replay::Schedule sch;
  bool ok = madtpu_shardkv_replay::parse_schedule(f, &sch);
  std::fclose(f);
  if (!ok || sch.groups > madtpu_shardkv_replay::ShardKvTester::N_GROUPS)
    return -1;
  return emit(madtpu_shardkv_replay::run_schedule(sch), out, cap);
}

int madtpu_ctrler_replay_run(const char* schedule, char* out, int cap) {
  std::lock_guard<std::mutex> lock(g_call_mutex);
  FILE* f = fmemopen((void*)schedule, std::strlen(schedule), "r");
  if (!f) return -1;
  madtpu_ctrler_replay::Schedule sch;
  bool ok = madtpu_ctrler_replay::parse_schedule(f, &sch);
  std::fclose(f);
  if (!ok) return -1;
  return emit(madtpu_ctrler_replay::run_schedule(sch), out, cap);
}

int madtpu_lincheck_run(const char* history) {
  std::lock_guard<std::mutex> lock(g_call_mutex);
  return madtpu_lincheck::check_history_text(history);
}

}  // extern "C"
