// Core of madtpu_shardkv_replay (sharded-stack differential replay),
// shared by the CLI binary (shardkv_replay_main.cpp) and the in-process
// C API (capi.cpp / libmadtpu.so -> madraft_tpu/simcore.py). See
// shardkv_replay_main.cpp for the schedule format and the bridge contract.
#pragma once

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "../shardkv/shardkv_tester.h"
#include "env_guard.h"

namespace madtpu_shardkv_replay {

using namespace shardkv;
using simcore::Addr;
using simcore::make_addr;
using simcore::MSEC;
using simcore::Sim;
using simcore::Task;


struct CfgEvent {
  uint64_t tick = 0;
  std::array<int, shard_ctrler::N_SHARDS> owner{};  // 0..G-1
};

struct AliveEvent {
  uint64_t tick = 0;
  int group = 0;
  uint64_t mask = 0;
};

struct FlipEvent {
  uint64_t tick = 0;  // commit tick of the flip (TPU slot_tick)
  int gid = 0;        // group index whose membership flips (0..G-1)
};

struct Schedule {
  int groups = 0;
  int nodes = 0;
  uint64_t ticks = 0;
  uint64_t ms_per_tick = 10;
  uint64_t seed = 0;
  std::string bug = "none";
  std::string raft_bug;             // raft-layer planted bug (MADTPU_BUG,
  //                                   raftcore raft.cpp / config.py RAFT_BUGS)
  // mode "schedule": reproduce the TPU's pre-drawn owner maps via Move ops.
  // mode "computed": the TPU's computed-ctrler composite — drive the REAL
  // 4A service with Join/Leave derived from the committed membership-flip
  // stream, so the C++ ctrler COMPUTES every config through its own
  // rebalance (server.rs:16-18 composed with shardkv server.rs:12-18).
  std::string mode = "schedule";
  std::string ctrl_bug = "none";    // 4A planted bug (MADTPU_CTRLER_BUG)
  std::vector<CfgEvent> cfgs;       // sorted by tick
  std::vector<AliveEvent> alives;   // sorted by tick
  std::vector<FlipEvent> flips;     // sorted by tick (mode "computed")
};

inline bool parse_schedule(FILE* f, Schedule* out) {
  char line[4096];
  while (std::fgets(line, sizeof line, f)) {
    if (line[0] == '#' || line[0] == '\n') continue;
    char kw[64];
    if (std::sscanf(line, "%63s", kw) != 1) continue;
    if (!std::strcmp(kw, "groups")) {
      std::sscanf(line, "%*s %d", &out->groups);
    } else if (!std::strcmp(kw, "nodes")) {
      std::sscanf(line, "%*s %d", &out->nodes);
    } else if (!std::strcmp(kw, "ticks")) {
      std::sscanf(line, "%*s %" SCNu64, &out->ticks);
    } else if (!std::strcmp(kw, "ms_per_tick")) {
      std::sscanf(line, "%*s %" SCNu64, &out->ms_per_tick);
    } else if (!std::strcmp(kw, "seed")) {
      std::sscanf(line, "%*s %" SCNu64, &out->seed);
    } else if (!std::strcmp(kw, "bug")) {
      char b[64];
      if (std::sscanf(line, "%*s %63s", b) == 1) out->bug = b;
      // same silent-skip guard as the raft bug below: an unknown service
      // bug name would set MADTPU_SHARDKV_BUG to something shardkv.h's
      // bug_mode() never matches and replay the correct service — the
      // whitelist IS bug_mode_of's name table, so they cannot drift
      if (!shardkv::is_known_service_bug(out->bug)) return false;
    } else if (!std::strcmp(kw, "raft_bug")) {
      char b[64] = {0};
      if (std::sscanf(line, "%*s %63s", b) == 1) out->raft_bug = b;
      if (!madtpu_tools::is_known_raft_bug(out->raft_bug)) return false;
    } else if (!std::strcmp(kw, "mode")) {
      char m[64] = {0};
      if (std::sscanf(line, "%*s %63s", m) == 1) out->mode = m;
      if (out->mode != "schedule" && out->mode != "computed") return false;
    } else if (!std::strcmp(kw, "ctrl_bug")) {
      char b[64] = {0};
      if (std::sscanf(line, "%*s %63s", b) == 1) out->ctrl_bug = b;
      // same whitelist-is-the-name-table guard as the service bug above
      if (!shard_ctrler::is_known_ctrler_bug(out->ctrl_bug)) return false;
    } else if (!std::strcmp(kw, "flip")) {
      FlipEvent ev;
      if (std::sscanf(line, "%*s %" SCNu64 " %d", &ev.tick, &ev.gid) != 2)
        continue;
      out->flips.push_back(ev);
    } else if (!std::strcmp(kw, "cfg")) {
      CfgEvent ev;
      int consumed = 0;
      if (std::sscanf(line, "%*s %" SCNu64 " %n", &ev.tick, &consumed) < 1)
        continue;
      const char* p = line + consumed;
      char* end = nullptr;
      for (auto& o : ev.owner) {
        o = int(std::strtol(p, &end, 10));
        p = end;
      }
      out->cfgs.push_back(ev);
    } else if (!std::strcmp(kw, "ev")) {
      AliveEvent ev;
      char kind[32];
      int consumed = 0;
      if (std::sscanf(line, "%*s %" SCNu64 " %31s %d %n", &ev.tick, kind,
                      &ev.group, &consumed) < 3 ||
          std::strcmp(kind, "alive"))
        continue;
      ev.mask = std::strtoull(line + consumed, nullptr, 16);
      out->alives.push_back(ev);
    }
  }
  if (out->groups <= 0 || out->nodes <= 0 || out->ticks == 0) return false;
  for (const auto& ev : out->cfgs)
    for (int o : ev.owner)
      if (o < 0 || o >= out->groups) return false;
  for (const auto& ev : out->alives)
    if (ev.group < 0 || ev.group >= out->groups) return false;
  for (const auto& ev : out->flips)
    if (ev.gid < 0 || ev.gid >= out->groups) return false;
  return true;
}

struct Flags {
  bool dup_apply = false;
  bool stale_read = false;
  uint64_t ops = 0;
  uint64_t gets = 0;
  uint64_t first_violation_ms = 0;
  // Global acked-write oracle, shared by every clerk in the process: an
  // append that completed (by ANY client) before a Get was invoked must
  // appear in that Get's output — the client-side form of the TPU fuzzer's
  // global interval oracle. Per-clerk-only checks cannot catch serve-frozen
  // staleness: a clerk's cached config only moves forward, so its OWN
  // tokens are always present in the frozen copy it might be served.
  std::map<std::string, std::vector<std::pair<uint64_t, std::string>>>
      acked;  // key -> (ack virtual time, token)
};

// One clerk's workload: append own tokens to shared per-shard keys, read
// back. Checks: every (anyone's) token acked before the Get's invoke is
// present (stale_read), and own tokens appear exactly once, in order
// (dup_apply — exactly-once across migration, check_clnt_appends style).
inline Task<void> clerk_task(Sim* sim, ShardKvTester* t, Flags* fl, int id,
                      uint64_t end_ns) {
  auto ck = t->make_client();
  uint64_t seq = 0;
  auto flag = [&](bool* which) {
    if (!fl->dup_apply && !fl->stale_read)
      fl->first_violation_ms = sim->now() / MSEC;
    *which = true;
  };
  char own_prefix[16];
  std::snprintf(own_prefix, sizeof own_prefix, "c%d.", id);
  while (sim->now() < end_ns) {
    std::string key(1, char('0' + int((seq + id) % shard_ctrler::N_SHARDS)));
    char token[48];
    std::snprintf(token, sizeof token, "c%d.%" PRIu64 ";", id, seq);
    seq++;
    co_await ck.append(key, token);
    fl->acked[key].emplace_back(sim->now(), token);
    fl->ops++;
    if (sim->now() >= end_ns) break;
    uint64_t invoke = sim->now();
    std::string v = co_await ck.get(key);
    fl->gets++;
    size_t own_pos = 0;
    for (const auto& [ack_t, tok] : fl->acked[key]) {
      size_t first = v.find(tok);
      if (first == std::string::npos) {
        // only writes acked before OUR invoke are guaranteed visible
        if (ack_t < invoke) flag(&fl->stale_read);
        continue;
      }
      if (tok.compare(0, std::strlen(own_prefix), own_prefix) == 0) {
        if (v.find(tok, first + tok.size()) != std::string::npos ||
            first < own_pos)
          flag(&fl->dup_apply);  // applied twice, or out of client order
        own_pos = first + tok.size();
      }
    }
  }
}

// Drive the real ctrler through the TPU's pre-drawn owner maps: initial
// Join of every group, then per config event one Move per changed shard
// (each Move is one ctrler config; groups chain through all of them with
// the full migration protocol — same reconfiguration pressure, class-level
// equivalence).
inline Task<void> config_driver(Sim* sim, ShardKvTester* t,
                         std::shared_ptr<CtrlerClerk> ck,
                         const Schedule* sch, uint64_t end_ns) {
  std::vector<int> all;
  for (int g = 0; g < sch->groups; g++) all.push_back(g);
  co_await t->joins(all);
  std::array<int, shard_ctrler::N_SHARDS> cur{};
  cur.fill(-1);
  for (const auto& ev : sch->cfgs) {
    uint64_t at = ev.tick * sch->ms_per_tick * MSEC;
    if (at >= end_ns) break;
    if (at > sim->now()) co_await sim->sleep(at - sim->now());
    for (size_t s = 0; s < shard_ctrler::N_SHARDS; s++) {
      if (cur[s] == ev.owner[s]) continue;
      co_await ck->move_(s, t->gid_of(ev.owner[s]));
      cur[s] = ev.owner[s];
    }
  }
}

// Composite mode: drive the real 4A service with Join/Leave ops DERIVED
// from the TPU's committed membership-flip stream, at the flips' commit
// ticks — the C++ ctrler COMPUTES every owner map through its own rebalance
// and the groups chain through those computed configs with the full
// migration protocol. Flip semantics mirror the TPU walker: toggle the
// group's membership, never emptying the member set.
inline Task<void> computed_config_driver(Sim* sim, ShardKvTester* t,
                                  std::shared_ptr<CtrlerClerk> ck,
                                  const Schedule* sch, uint64_t end_ns) {
  std::vector<int> all;
  for (int g = 0; g < sch->groups; g++) all.push_back(g);
  co_await t->joins(all);  // TPU config 0: every group is a member
  std::vector<bool> member(sch->groups, true);
  int n_mem = sch->groups;
  for (const auto& ev : sch->flips) {
    uint64_t at = ev.tick * sch->ms_per_tick * MSEC;
    if (at >= end_ns) break;
    if (at > sim->now()) co_await sim->sleep(at - sim->now());
    if (member[ev.gid]) {
      if (n_mem <= 1) continue;  // >=1 member floor (walker semantics)
      co_await t->leave(ev.gid);
      member[ev.gid] = false;
      n_mem--;
    } else {
      co_await t->join(ev.gid);
      member[ev.gid] = true;
      n_mem++;
    }
  }
}

// The composite divergence class: replay the SAME flip-derived op stream
// into two ShardInfo replicas with rotated tie-breaks (the ctrler-leg
// idiom, ctrler_replay_core.h) — under rotate_tiebreak their config
// histories must disagree, which is exactly the divergence the TPU's
// composite oracle (VIOLATION_SHARD_CTRL_STALE) flags when a 4B group
// adopts a rotated replica's map.
inline int flips_diverge_across_replicas(const Schedule& sch) {
  using shard_ctrler::CtrlOp;
  using shard_ctrler::Gid;
  using shard_ctrler::ShardInfo;
  if (sch.ctrl_bug != "rotate_tiebreak") return 0;
  ShardInfo a, b;
  std::vector<bool> member(sch.groups, true);
  int n_mem = sch.groups;
  auto srvs_of = [](Gid gid) {
    return std::vector<Addr>{make_addr(0, 1, unsigned(gid - 100), 0)};
  };
  std::map<Gid, std::vector<Addr>> all;
  for (int g = 0; g < sch.groups; g++) all[100 + g] = srvs_of(100 + g);
  auto apply_both = [&](const CtrlOp& op) {
    madtpu_tools::EnvGuard bg("MADTPU_CTRLER_BUG", "rotate_tiebreak");
    {
      madtpu_tools::EnvGuard rg("MADTPU_CTRLER_ROT", "0");
      a.apply(op);
    }
    {
      madtpu_tools::EnvGuard rg("MADTPU_CTRLER_ROT", "1");
      b.apply(op);
    }
  };
  apply_both(CtrlOp::join(all));
  for (const auto& ev : sch.flips) {
    Gid gid = 100 + ev.gid;
    if (member[ev.gid]) {
      if (n_mem <= 1) continue;
      apply_both(CtrlOp::leave({gid}));
      member[ev.gid] = false;
      n_mem--;
    } else {
      apply_both(CtrlOp::join({{gid, srvs_of(gid)}}));
      member[ev.gid] = true;
      n_mem++;
    }
  }
  return a.configs == b.configs ? 0 : 1;
}

inline Task<void> fault_driver(Sim* sim, ShardKvTester* t, const Schedule* sch,
                        uint64_t end_ns) {
  std::vector<uint64_t> alive(sch->groups, ~0ull);
  for (const auto& ev : sch->alives) {
    uint64_t at = ev.tick * sch->ms_per_tick * MSEC;
    if (at >= end_ns) break;
    if (at > sim->now()) co_await sim->sleep(at - sim->now());
    for (int i = 0; i < sch->nodes; i++) {
      bool was = (alive[ev.group] >> i) & 1, now = (ev.mask >> i) & 1;
      if (was && !now) t->shutdown_server(ev.group, i);
      if (!was && now) co_await sim->spawn(t->start_server(ev.group, i));
    }
    alive[ev.group] = ev.mask;
  }
}

inline Task<void> replay_driver(Sim* sim, ShardKvTester* t, Flags* fl,
                       const Schedule* sch) {
  co_await t->init();
  uint64_t end_ns = sch->ticks * sch->ms_per_tick * MSEC;
  auto ctrl_ck = std::make_shared<CtrlerClerk>(
      sim, std::vector<Addr>{make_addr(0, 0, 1, 0), make_addr(0, 0, 1, 1),
                             make_addr(0, 0, 1, 2)},
      9000);
  std::vector<simcore::TaskRef<void>> tasks;
  tasks.push_back(sim->spawn(
      Addr(make_addr(0, 0, 3, 90)),
      sch->mode == "computed"
          ? computed_config_driver(sim, t, ctrl_ck, sch, end_ns)
          : config_driver(sim, t, ctrl_ck, sch, end_ns)));
  tasks.push_back(
      sim->spawn(Addr(make_addr(0, 0, 3, 91)), fault_driver(sim, t, sch, end_ns)));
  for (int c = 0; c < 8; c++)
    tasks.push_back(sim->spawn(Addr(make_addr(0, 0, 3, 92 + c)),
                               clerk_task(sim, t, fl, c, end_ns)));
  if (end_ns > sim->now()) co_await sim->sleep(end_ns - sim->now());
}


// Run a parsed schedule; returns the one-line JSON report ("" = deadlock).
// The bug mode rides in the schedule; shardkv::bug_mode() reads the env per
// call, so it is set for the run and restored afterwards (in-process
// callers interleave buggy and clean replays; capi.cpp serializes).
inline std::string run_schedule(const Schedule& sch) {
  madtpu_tools::EnvGuard guard(
      "MADTPU_SHARDKV_BUG", sch.bug != "none" ? sch.bug.c_str() : nullptr);
  madtpu_tools::EnvGuard raft_guard(
      "MADTPU_BUG", !sch.raft_bug.empty() ? sch.raft_bug.c_str() : nullptr);
  // Composite mode's divergence class is checked OUTSIDE the service run
  // (two rotated ShardInfo replicas over the same committed op stream) —
  // the in-process service can only run ONE rot at a time, so the full
  // replay runs it uniformly (rot 1: rotated-but-consistent maps, the
  // liveness half) while `diverged` carries the per-replica class.
  int diverged = sch.mode == "computed" ? flips_diverge_across_replicas(sch) : 0;
  madtpu_tools::EnvGuard cbg(
      "MADTPU_CTRLER_BUG",
      sch.ctrl_bug != "none" ? sch.ctrl_bug.c_str() : nullptr);
  madtpu_tools::EnvGuard crg(
      "MADTPU_CTRLER_ROT", sch.ctrl_bug != "none" ? "1" : nullptr);
  std::string out;
  if (sch.groups <= ShardKvTester::N_GROUPS) {
    Sim sim(sch.seed);
    ShardKvTester t(&sim, sch.nodes, /*unreliable=*/true,
                    /*max_raft_state=*/1000);
    Flags fl;
    if (sim.run(replay_driver(&sim, &t, &fl, &sch))) {
      char buf[512];
      std::snprintf(
          buf, sizeof buf,
          "{\"dup_apply\": %d, \"stale_read\": %d, \"diverged\": %d, "
          "\"ops\": %" PRIu64 ", \"gets\": %" PRIu64
          ", \"first_violation_ms\": %" PRIu64 ", \"rpcs\": %" PRIu64 "}",
          (int)fl.dup_apply, (int)fl.stale_read, diverged, fl.ops, fl.gets,
          fl.first_violation_ms, sim.msg_count() / 2);
      out = buf;
    }
  }
  return out;
}

}  // namespace madtpu_shardkv_replay
