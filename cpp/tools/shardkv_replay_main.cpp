// madtpu_shardkv_replay — the simcore end of the SHARDED-KV differential
// bridge (extending cpp/tools/replay_main.cpp, which stops at raw raft).
//
// The batched TPU shardkv fuzzer (madraft_tpu/tpusim/shardkv.py) reports a
// violating deployment as (seed, cluster_id); the Python side
// (madraft_tpu/bridge.py extract_shardkv_schedule) re-runs that deployment
// and exports its CONFIG SCHEDULE (the pre-drawn owner maps + activation
// ticks) and FAULT SCHEDULE (per-group alive masks), plus which service bug
// mode was injected. This binary replays the same reconfiguration pressure
// against the full C++ shardkv stack on simcore — ctrler cluster + G raft
// groups + real migration/GC — with the SAME protocol bug enabled
// (shardkv.h bug_mode()), and reports which violation classes its
// client-side checkers observed. Schedules, not PRNG streams, are the
// interchange; equivalence is class-level (SURVEY.md §7, and the replay
// contract of /root/reference/README.md:42-55).
//
// Schedule format (line-based; '#' comments):
//   groups <G>
//   nodes <N>                  # servers per group
//   ticks <T>
//   ms_per_tick <ms>
//   seed <u64>
//   bug <none|drop_dup_table|serve_frozen>
//   cfg <tick> <o0> ... <o9>   # owner group (0..G-1) per shard, activation tick
//   ev <tick> alive <g> <hexmask>   # group g's per-node alive bits
//
// The TPU controller is a pre-drawn schedule; here the real ctrler service
// reproduces each owner map via Move ops (every group chains through the
// intermediate configs with the full pull/install/ack protocol). Client-side
// checkers (check_clnt_appends style, kvraft/tests.rs:21-43):
//   dup_apply  — a clerk's own append token appears twice or out of order
//   stale_read — a Get is missing an append the same clerk had already
//                completed before the Get was invoked
// Output: one JSON line; exit 0 if the replay ran.
// Core logic lives in shardkv_replay_core.h, shared with the in-process
// C API (capi.cpp -> libmadtpu.so -> madraft_tpu/simcore.py).
#include "shardkv_replay_core.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <schedule-file>\n", argv[0]);
    return 2;
  }
  FILE* f = std::fopen(argv[1], "r");
  madtpu_shardkv_replay::Schedule sch;
  bool ok = f && madtpu_shardkv_replay::parse_schedule(f, &sch);
  if (f) std::fclose(f);
  if (!ok) {
    std::fprintf(stderr, "bad schedule file: %s\n", argv[1]);
    return 2;
  }
  if (sch.groups > madtpu_shardkv_replay::ShardKvTester::N_GROUPS) {
    std::fprintf(stderr, "at most %d groups supported\n",
                 madtpu_shardkv_replay::ShardKvTester::N_GROUPS);
    return 2;
  }
  std::string report = madtpu_shardkv_replay::run_schedule(sch);
  if (report.empty()) {
    std::fprintf(stderr, "sim deadlocked\n");
    return 2;
  }
  std::printf("%s\n", report.c_str());
  return 0;
}
