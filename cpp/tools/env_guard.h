// Scoped set/restore of an env-riding knob (majority override, shardkv bug
// mode). The C API serializes all calls behind one mutex (capi.cpp), so the
// process-global env is never mutated concurrently; the guard keeps the
// restore correct across every return path.
#pragma once

#include <cstdlib>
#include <string>

namespace madtpu_tools {

// Single source of the raft-layer planted-bug whitelist on the C++ side
// (mirrors config.py RAFT_BUGS; the bug() sites live in raftcore/raft.cpp).
// Both schedule parsers reject unknown names — a silently-skipped bug would
// make a clean replay read as "TPU false positive".
inline bool is_known_raft_bug(const std::string& name) {
  return name == "commit_any_term" || name == "grant_any_vote" ||
         name == "forget_voted_for" || name == "no_truncate" ||
         name == "ack_before_fsync";
}

struct EnvGuard {
  const char* name;
  std::string saved;
  bool had;

  EnvGuard(const char* n, const char* value) : name(n) {
    const char* old = std::getenv(n);
    had = old != nullptr;
    if (had) saved = old;
    if (value)
      setenv(n, value, 1);
    else
      unsetenv(n);
  }
  ~EnvGuard() {
    if (had)
      setenv(name, saved.c_str(), 1);
    else
      unsetenv(name);
  }
  EnvGuard(const EnvGuard&) = delete;
  EnvGuard& operator=(const EnvGuard&) = delete;
};

}  // namespace madtpu_tools
