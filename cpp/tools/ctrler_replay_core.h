// madtpu_ctrler_replay core — the simcore end of the Lab-4A differential
// bridge (madraft_tpu/bridge.py extract_ctrler_schedule). Shared between the
// CLI binary (ctrler_replay_main.cpp) and the in-process C API (capi.cpp).
//
// The batched TPU 4A fuzzer (madraft_tpu/tpusim/ctrler.py) commits
// Join/Leave/Move/Query ops through a raft cluster and checks balance,
// minimal transfers, replica determinism, and historical queries on device.
// The Python exporter replays one (seed, cluster), walks its committed
// shadow log, dedups clerk retries, filters to the EFFECTIVE ops (the ones
// the service actually applied — both backends reject a Join of a member, a
// Leave of a non-member, a Move to a non-member, and any mutation past the
// TPU history capacity), and ships them here. This tool applies the stream
// to the REAL ShardInfo state machine (cpp/shard_ctrler/ctrler.h) with the
// SAME planted bug enabled (ctrl_bug_mode) and reports which violation
// classes its own checkers observed:
//   balance_bad  — a Join/Leave config is unbalanced or orphans a shard
//                  (ctrler_tester.h's check; TPU CTRL_BALANCE)
//   minimal_bad  — a Join/Leave moved more shards than the closed-form
//                  minimum (TPU CTRL_MINIMAL)
//   diverged     — two replicas with rotated tie-breaks disagree on the
//                  config history (TPU CTRL_DIVERGE / CTRL_QUERY)
//   map_match    — bug-free runs only: the final owner map and config count
//                  equal the TPU walker's EXACTLY (both backends implement
//                  the same canonical rebalance spec; gid g <-> Gid g+1)
//
// Schedule format (line-based; '#' comments):
//   gids <NG>
//   bug <none|rotate_tiebreak|greedy_rebalance|full_reshuffle>
//   op join <g0> [g1 ...] | op leave <g0> [g1 ...]   # 1..join_max gids
//   op move <shard> <gid> | op query <num>
//   expect_cfgs <n>
//   expect_owner <o0> ... <o9>       # -1 = unowned (TPU gid index space)
#pragma once

#include <cinttypes>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "../shard_ctrler/ctrler.h"
#include "env_guard.h"

namespace madtpu_ctrler_replay {

using shard_ctrler::Config;
using shard_ctrler::CtrlOp;
using shard_ctrler::Gid;
using shard_ctrler::N_SHARDS;
using shard_ctrler::ShardInfo;

struct OpLine {
  int kind = 0;  // 0 join(set) / 1 leave(set) / 2 move(a=shard, b=gid)
  //                3 query(a=num)
  uint64_t a = 0, b = 0;
  std::vector<uint64_t> set;  // join/leave gid set (1..join_max gids — the
  //                             TPU layer's multi-gid ops; msg.rs:20-37)
};

struct Schedule {
  uint64_t gids = 5;
  std::string bug = "none";
  std::vector<OpLine> ops;
  long long expect_cfgs = -1;
  std::vector<long long> expect_owner;  // -1 = unowned
};

inline bool parse_schedule(FILE* f, Schedule* out) {
  char line[512];
  while (std::fgets(line, sizeof line, f)) {
    if (line[0] == '#' || line[0] == '\n') continue;
    char kw[32] = {0};
    if (std::sscanf(line, "%31s", kw) != 1) continue;
    if (!std::strcmp(kw, "gids")) {
      std::sscanf(line, "%*s %" SCNu64, &out->gids);
    } else if (!std::strcmp(kw, "bug")) {
      char b[64] = {0};
      if (std::sscanf(line, "%*s %63s", b) == 1) out->bug = b;
      // reject unknown names — a silently-skipped bug would make a clean
      // replay read as "TPU false positive" (same guard as the other legs)
      if (!shard_ctrler::is_known_ctrler_bug(out->bug)) return false;
    } else if (!std::strcmp(kw, "op")) {
      char k[32] = {0};
      OpLine op;
      int got = std::sscanf(line, "%*s %31s %" SCNu64 " %" SCNu64, k, &op.a,
                            &op.b);
      if (got < 2) return false;
      if (!std::strcmp(k, "join")) op.kind = 0;
      else if (!std::strcmp(k, "leave")) op.kind = 1;
      else if (!std::strcmp(k, "move")) op.kind = 2;
      else if (!std::strcmp(k, "query")) op.kind = 3;
      else return false;
      // a truncated "op move <shard>" would silently replay move(_, gid 0)
      // — a different op stream reading as "TPU false positive"
      if (op.kind == 2 && got < 3) return false;
      if (op.kind <= 1) {
        // join/leave carry a variable-length gid set: re-scan past the
        // keyword+kind and collect every remaining integer
        const char* p = line;
        for (int skip = 0; skip < 2 && *p; skip++) {
          while (*p == ' ' || *p == '\t') p++;
          while (*p && *p != ' ' && *p != '\t' && *p != '\n') p++;
        }
        char* end = nullptr;
        for (;;) {
          uint64_t v = std::strtoull(p, &end, 10);
          if (end == p) break;
          op.set.push_back(v);
          p = end;
        }
        if (op.set.empty()) return false;
      }
      out->ops.push_back(op);
    } else if (!std::strcmp(kw, "expect_cfgs")) {
      std::sscanf(line, "%*s %lld", &out->expect_cfgs);
    } else if (!std::strcmp(kw, "expect_owner")) {
      const char* p = line + std::strlen("expect_owner");
      char* end = nullptr;
      for (size_t s = 0; s < N_SHARDS; s++) {
        long long v = std::strtoll(p, &end, 10);
        if (end == p) return false;
        out->expect_owner.push_back(v);
        p = end;
      }
    }
  }
  return true;
}

// The closed-form minimal move count for old config -> new member set:
// orphans must move; overloaded members shed down to best-case targets
// (ceil targets to the largest retained loads, ties by ascending gid) —
// the same formula as ctrler.py _min_moves.
inline size_t min_moves(const Config& before,
                        const std::map<Gid, std::vector<simcore::Addr>>& groups) {
  std::map<Gid, size_t> retained;
  for (auto& [gid, _] : groups) retained[gid] = 0;
  size_t orphans = 0;
  for (size_t s = 0; s < N_SHARDS; s++) {
    auto it = retained.find(before.shards[s]);
    if (it == retained.end())
      orphans++;
    else
      it->second++;
  }
  size_t k = groups.size();
  if (!k) return 0;
  size_t q = N_SHARDS / k, r = N_SHARDS % k;
  std::vector<std::pair<Gid, size_t>> order(retained.begin(), retained.end());
  std::stable_sort(order.begin(), order.end(),
                   [](const auto& a, const auto& b) {
                     return a.second != b.second ? a.second > b.second
                                                 : a.first < b.first;
                   });
  size_t shed = 0;
  for (size_t i = 0; i < order.size(); i++) {
    size_t tgt = q + (i < r ? 1 : 0);
    if (order[i].second > tgt) shed += order[i].second - tgt;
  }
  return orphans + shed;
}

inline std::string run_schedule(const Schedule& sch) {
  madtpu_tools::EnvGuard bug_guard(
      "MADTPU_CTRLER_BUG", sch.bug == "none" ? nullptr : sch.bug.c_str());
  bool rotate = sch.bug == "rotate_tiebreak";

  ShardInfo a, b;  // b: the rot=1 replica, used for the divergence class
  int balance_bad = 0, minimal_bad = 0;
  for (const auto& op : sch.ops) {
    CtrlOp c;
    switch (op.kind) {
      case 0: {
        std::map<Gid, std::vector<simcore::Addr>> groups;
        for (uint64_t g : op.set)
          groups[Gid(g) + 1] = {simcore::Addr(g + 1)};
        c = CtrlOp::join(std::move(groups));
        break;
      }
      case 1: {
        std::vector<Gid> gids;
        for (uint64_t g : op.set) gids.push_back(Gid(g) + 1);
        c = CtrlOp::leave(std::move(gids));
        break;
      }
      case 2:
        c = CtrlOp::move_(op.a, Gid(op.b) + 1);
        break;
      default:
        c = CtrlOp::query(op.a);
        break;
    }
    Config before = a.configs.back();
    {
      madtpu_tools::EnvGuard rg("MADTPU_CTRLER_ROT", "0");
      a.apply(c);
    }
    if (rotate) {
      madtpu_tools::EnvGuard rg("MADTPU_CTRLER_ROT", "1");
      b.apply(c);
    }
    if (op.kind == 0 || op.kind == 1) {
      const Config& now = a.configs.back();
      if (now.groups.empty()) continue;  // checks stand down at k = 0
      // balance: every shard on a member; loads max-min <= 1
      // (shard_ctrler/ctrler_tester.h's check())
      std::map<Gid, size_t> count;
      for (auto& [gid, _] : now.groups) count[gid] = 0;
      bool orphan = false;
      for (size_t s = 0; s < N_SHARDS; s++) {
        auto it = count.find(now.shards[s]);
        if (it == count.end())
          orphan = true;
        else
          it->second++;
      }
      size_t cmax = 0, cmin = N_SHARDS;
      for (auto& [_, n] : count) {
        cmax = std::max(cmax, n);
        cmin = std::min(cmin, n);
      }
      if (orphan || cmax - cmin > 1) balance_bad++;
      // minimality vs the closed form
      size_t moved = 0;
      for (size_t s = 0; s < N_SHARDS; s++)
        if (before.shards[s] != now.shards[s]) moved++;
      if (moved != min_moves(before, now.groups)) minimal_bad++;
    }
  }
  int diverged = rotate && !(a.configs == b.configs) ? 1 : 0;
  int map_match = -1;  // -1 = not checked (bug runs have no expected map)
  if (sch.bug == "none" && sch.expect_owner.size() == N_SHARDS) {
    const Config& fin = a.configs.back();
    map_match = 1;
    for (size_t s = 0; s < N_SHARDS; s++) {
      long long want = sch.expect_owner[s];
      Gid got = fin.shards[s];
      if (want < 0 ? got != 0 : got != Gid(want) + 1) map_match = 0;
    }
    if (sch.expect_cfgs >= 0 && (long long)fin.num != sch.expect_cfgs)
      map_match = 0;
  }
  char buf[256];
  std::snprintf(buf, sizeof buf,
                "{\"balance_bad\": %d, \"minimal_bad\": %d, \"diverged\": %d, "
                "\"map_match\": %d, \"configs\": %llu}",
                balance_bad, minimal_bad, diverged, map_match,
                (unsigned long long)a.configs.back().num);
  return buf;
}

}  // namespace madtpu_ctrler_replay
