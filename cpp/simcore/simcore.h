// simcore — deterministic discrete-event simulation runtime for distributed
// systems tests, built from scratch as the madsim-equivalent L0 of this
// framework (contract: SURVEY.md §2.6; the reference call sites are the
// madsim 0.1.1 API used by /root/reference — Runtime/Handle/LocalHandle,
// net, fs, time, task, rand).
//
// Design (deliberately NOT a port; madsim is Rust+tokio-style):
//   * single-threaded; virtual time advances only via the event queue
//   * events ordered by (virtual_time, seq) — seq is a monotonic counter, so
//     ties break FIFO and runs are bit-reproducible from the seed (no
//     address-based ordering anywhere, ASLR-proof)
//   * node code = C++20 coroutines; ONLY leaf awaitables (sleep, rpc call,
//     channel recv, task join) — no arbitrary nesting, which keeps kill()
//     (crash a node: destroy its coroutine frames, keep its filesystem)
//     safe: every pending continuation is guarded by a live-task check
//     before resume, so a killed task's dangling frame is never touched
//   * RPC payloads move as typed in-process values (std::any) —
//     serialization is semantically irrelevant in-process; the persistence
//     path (fs) uses real byte encoding, matching the reference's
//     "state"/"snapshot" file contract
//   * fault injection is first-class: per-message loss + latency draws from
//     the seeded RNG, whole-node connect/disconnect (both directions),
//     pairwise connect2/disconnect2, kill/respawn
//   * determinism check: a rolling trace hash folded at every event pop;
//     two runs with the same seed must produce identical hashes (the
//     MADSIM_TEST_CHECK_DETERMINISTIC analogue, reference README.md:81-87).
#pragma once

#include <any>
#include <cassert>
#include <coroutine>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <optional>
#include <queue>
#include <random>
#include <set>
#include <string>
#include <typeindex>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace simcore {

using Addr = uint32_t;  // IPv4-style encoded address (port irrelevant in-sim)
using Bytes = std::vector<uint8_t>;

constexpr Addr make_addr(unsigned a, unsigned b, unsigned c, unsigned d) {
  return (Addr(a) << 24) | (Addr(b) << 16) | (Addr(c) << 8) | Addr(d);
}
inline std::string addr_str(Addr a) {
  char buf[24];
  std::snprintf(buf, sizeof buf, "%u.%u.%u.%u", (a >> 24) & 255, (a >> 16) & 255,
                (a >> 8) & 255, a & 255);
  return buf;
}

constexpr uint64_t USEC = 1000;
constexpr uint64_t MSEC = 1000 * USEC;
constexpr uint64_t SEC = 1000 * MSEC;

// ---------------------------------------------------------------- tracing
// Per-module diagnostic tracing, the analogue of the reference's RUST_LOG
// filtering (/root/reference/README.md:57-61, test.yml:23). Off by default;
// enable with e.g.
//   MADTPU_LOG=raft                 (one module)
//   MADTPU_LOG=raft,shardkv         (several)
//   MADTPU_LOG=all                  (everything)
// Lines carry the VIRTUAL timestamp and the current node, so a trace of a
// failing seed reads like the reference's madsim logger output.
//   MT_LOG("raft", "term %llu: vote granted to %u", term, cand);
namespace log_detail {
inline bool module_enabled(const char* module) {
  static const std::string filter = [] {
    const char* e = std::getenv("MADTPU_LOG");
    return std::string(e ? e : "");
  }();
  if (filter.empty()) return false;
  if (filter == "all" || filter == "1") return true;
  size_t pos = 0;
  const std::string m(module);
  while (pos < filter.size()) {
    size_t comma = filter.find(',', pos);
    if (comma == std::string::npos) comma = filter.size();
    if (filter.compare(pos, comma - pos, m) == 0) return true;
    pos = comma + 1;
  }
  return false;
}
void log_line(const char* module, const char* fmt, ...);  // defined in .cpp
}  // namespace log_detail

#define MT_LOG(module, ...)                                 \
  do {                                                      \
    if (::simcore::log_detail::module_enabled(module))      \
      ::simcore::log_detail::log_line(module, __VA_ARGS__); \
  } while (0)

class Sim;

// ------------------------------------------------------------------ Task<T>
// Lazy coroutine. Spawn on a node via Sim::spawn; the returned TaskRef can be
// co_awaited (join), aborted, or dropped (the task keeps running — detach is
// the default, like the reference's spawn(..).detach()).
template <class T>
struct JoinState {
  bool done = false;
  bool aborted = false;
  std::optional<T> value;
  std::vector<std::function<void()>> waiters;  // scheduled on completion
};
template <>
struct JoinState<void> {
  bool done = false;
  bool aborted = false;
  std::vector<std::function<void()>> waiters;
};

namespace detail {
template <class T>
struct PromiseBase {
  std::shared_ptr<JoinState<T>> js = std::make_shared<JoinState<T>>();
  Sim* sim = nullptr;
  uint64_t task_id = 0;
  ~PromiseBase() {
    // A frame destroyed before completion (kill/abort) never runs its
    // waiters; clear them here to break the JoinState<->waiter-closure
    // reference cycle (waiters commonly capture a TaskRef that owns js).
    if (!js->done) js->waiters.clear();
  }
  std::suspend_always initial_suspend() noexcept { return {}; }
  struct FinalAwaiter {
    bool await_ready() noexcept { return false; }
    template <class P>
    void await_suspend(std::coroutine_handle<P> h) noexcept;
    void await_resume() noexcept {}
  };
  FinalAwaiter final_suspend() noexcept { return {}; }
  void unhandled_exception() noexcept {
    std::fprintf(stderr, "simcore: unhandled exception in task\n");
    std::abort();
  }
};
}  // namespace detail

template <class T>
class Task {
 public:
  struct promise_type : detail::PromiseBase<T> {
    Task get_return_object() {
      return Task(std::coroutine_handle<promise_type>::from_promise(*this));
    }
    void return_value(T v) { this->js->value = std::move(v); }
  };
  explicit Task(std::coroutine_handle<promise_type> h) : h_(h) {}
  Task(Task&& o) noexcept : h_(std::exchange(o.h_, nullptr)) {}
  Task(const Task&) = delete;
  ~Task() {
    if (h_) h_.destroy();  // never spawned
  }
  std::coroutine_handle<promise_type> release() { return std::exchange(h_, nullptr); }

  // `co_await some_task()` = spawn on the current node, then join.
  auto operator co_await() &&;

 private:
  std::coroutine_handle<promise_type> h_;
};

template <>
class Task<void> {
 public:
  struct promise_type : detail::PromiseBase<void> {
    Task get_return_object() {
      return Task(std::coroutine_handle<promise_type>::from_promise(*this));
    }
    void return_void() {}
  };
  explicit Task(std::coroutine_handle<promise_type> h) : h_(h) {}
  Task(Task&& o) noexcept : h_(std::exchange(o.h_, nullptr)) {}
  Task(const Task&) = delete;
  ~Task() {
    if (h_) h_.destroy();
  }
  std::coroutine_handle<promise_type> release() { return std::exchange(h_, nullptr); }

  // `co_await some_task()` = spawn on the current node, then join.
  auto operator co_await() &&;

 private:
  std::coroutine_handle<promise_type> h_;
};

// Non-owning reference to a spawned task: join (co_await) / abort / detach.
template <class T>
class TaskRef {
 public:
  TaskRef() = default;
  TaskRef(std::shared_ptr<JoinState<T>> js, uint64_t id, Sim* sim)
      : js_(std::move(js)), id_(id), sim_(sim) {}
  bool valid() const { return js_ != nullptr; }
  bool done() const { return js_ && js_->done; }
  uint64_t id() const { return id_; }
  void abort();                              // kill just this task
  void add_callback(std::function<void()> f);  // run (as event) on completion

  // Awaitable (join). Awaiting an aborted task never resumes.
  bool await_ready() const { return js_->done; }
  void await_suspend(std::coroutine_handle<> h);
  T await_resume() const {
    if constexpr (!std::is_void_v<T>) return *js_->value;
  }
  const std::optional<T>& value() const
    requires(!std::is_void_v<T>)
  {
    return js_->value;
  }

 private:
  std::shared_ptr<JoinState<T>> js_;
  uint64_t id_ = 0;
  Sim* sim_ = nullptr;
};

// ------------------------------------------------------------------ Channel
// Unbounded single-consumer channel (the reference's apply channel,
// raft.rs:26-37). recv() returns nullopt once closed and drained.
template <class T>
class Channel {
 public:
  struct State {
    std::deque<T> q;
    std::vector<std::function<void()>> waiters;
    bool closed = false;
  };
  Channel() : st_(std::make_shared<State>()) {}
  void send(T v);
  void close();
  bool empty() const { return st_->q.empty(); }
  struct RecvAwaiter {
    Sim* sim;
    std::shared_ptr<State> st;
    bool await_ready() const { return !st->q.empty() || st->closed; }
    void await_suspend(std::coroutine_handle<> h);
    std::optional<T> await_resume() {
      if (st->q.empty()) return std::nullopt;  // closed
      T v = std::move(st->q.front());
      st->q.pop_front();
      return v;
    }
  };
  RecvAwaiter recv();

 private:
  std::shared_ptr<State> st_;
};

// ---------------------------------------------------------------------- Sim
struct NetConfig {
  // reference knobs: packet_loss_rate + send_latency range
  // (tester.rs:127-137: unreliable = 10% loss, 1..27ms latency)
  double packet_loss_rate = 0.0;
  uint64_t send_latency_min = 1 * MSEC;
  uint64_t send_latency_max = 10 * MSEC;
};

class Sim {
 public:
  explicit Sim(uint64_t seed);
  ~Sim();
  static Sim* current();  // like Handle::current()

  // ---- time (virtual, ns)
  uint64_t now() const { return now_; }
  uint64_t seed() const { return seed_; }
  struct SleepAwaiter {
    Sim* sim;
    uint64_t dur;
    bool await_ready() const { return false; }
    void await_suspend(std::coroutine_handle<> h);
    void await_resume() {}
  };
  SleepAwaiter sleep(uint64_t ns) { return {this, ns}; }

  // ---- rng (seeded; the only randomness allowed in node code)
  uint64_t rand_u64() { return rng_(); }
  uint64_t rand_range(uint64_t lo, uint64_t hi) {  // [lo, hi)
    return lo + rand_u64() % (hi - lo);
  }
  double rand_f64() { return (rand_u64() >> 11) * (1.0 / 9007199254740992.0); }
  bool rand_bool(double p) { return rand_f64() < p; }

  // ---- tasks
  template <class T>
  TaskRef<T> spawn(Addr node, Task<T> t);
  template <class T>
  TaskRef<T> spawn(Task<T> t) {  // on current node
    return spawn(cur_addr_, std::move(t));
  }
  void abort_task(uint64_t task_id);
  void kill(Addr node);  // crash: destroy tasks + handlers; fs survives
  Addr cur_addr() const { return cur_addr_; }
  uint64_t cur_task() const { return cur_task_; }

  // ---- net topology & stats
  NetConfig& net_config() { return netcfg_; }
  void connect(Addr a) { node_connected_[a] = true; }
  void disconnect(Addr a) { node_connected_[a] = false; }
  bool is_connected(Addr a) {
    auto it = node_connected_.find(a);
    return it == node_connected_.end() ? true : it->second;
  }
  void connect2(Addr a, Addr b) {
    blocked_pairs_.erase({a, b});
    blocked_pairs_.erase({b, a});
  }
  void disconnect2(Addr a, Addr b) {
    blocked_pairs_.insert({a, b});
    blocked_pairs_.insert({b, a});
  }
  uint64_t msg_count() const { return msg_count_; }

  // ---- typed RPC. Req must define `using Reply = ...`. Handlers belong to
  // the registering node and are wiped by kill() (so calls to a dead node
  // time out, like the reference's crashed peers).
  // CAUTION: message types carrying std::string members must declare a
  // constructor (non-aggregate). gcc 12 bitwise-relocates aggregate prvalues
  // across coroutine boundaries without running move ctors, which corrupts
  // SSO strings (vectors/PODs survive). See the note in kvraft/rsm.h.
  template <class Req>
  void add_rpc_handler(std::function<Task<typename Req::Reply>(Req)> h);
  template <class Req>
  auto call_timeout(Addr dst, Req req, uint64_t timeout_ns);

  // ---- fs: per-node persistent named files (survive kill; the reference's
  // "state"/"snapshot" contract, raft.rs:173-211, read by testers via
  // fs.get_file_size, tester.rs:155)
  void fs_write(const std::string& name, Bytes data) {
    fs_[cur_addr_][name] = std::move(data);
  }
  std::optional<Bytes> fs_read(const std::string& name) {
    return fs_read_at(cur_addr_, name);
  }
  // addr-explicit variants: node code that runs synchronously from a
  // tester-context call (e.g. RaftHandle::start persisting before return)
  // still targets its own node's disk
  void fs_write_at(Addr node, const std::string& name, Bytes data) {
    fs_[node][name] = std::move(data);
  }
  std::optional<Bytes> fs_read_at(Addr node, const std::string& name) {
    auto& files = fs_[node];
    auto it = files.find(name);
    if (it == files.end()) return std::nullopt;
    return it->second;
  }
  size_t fs_size(Addr node, const std::string& name) {
    auto it = fs_[node].find(name);
    return it == fs_[node].end() ? 0 : it->second.size();
  }

  // ---- run loop: drives events until `main` completes. Returns false on
  // deadlock (no runnable events while main is still pending).
  bool run(Task<void> main);

  // Per-run liveness watchdog, enabled by the test runner (main.cpp) and off
  // by default so the replay tools can run unbounded schedules. Mirrors the
  // reference's 120 s per-test panic (/root/reference/src/raft/tester.rs:
  // 353-358, kvraft/tester.rs:62-67, shardkv/tester.rs:226-231) and adds a
  // virtual-time cap so a livelock that burns virtual time (retry loops with
  // sleeps — the seed-7036 shape) is distinguishable from a real-time-slow
  // test: the abort names the test and both clocks.
  struct Watchdog {
    bool enabled = false;
    double real_cap_s = 120.0;  // reference parity
    double virt_cap_s = 600.0;  // ~10x the slowest legit test (61 s virt)
    const char* (*name_fn)() = nullptr;  // current test name for the abort
  };
  static Watchdog& watchdog() {
    static Watchdog w;
    return w;
  }
  uint64_t trace_hash() const { return trace_hash_; }
  // Observer invoked with the final trace hash at the end of each run();
  // the test runner uses it for the double-run determinism check
  // (MADTPU_TEST_CHECK_DETERMINISTIC, reference README.md:81-87).
  static std::function<void(uint64_t)>& trace_observer() {
    static std::function<void(uint64_t)> f;
    return f;
  }

  // ---- internals (used by awaitable/promise glue; not user API)
  void schedule(uint64_t at, std::function<void()> fn);
  bool task_live(uint64_t tid) const { return live_.count(tid) != 0; }
  void resume_in_context(uint64_t tid, std::coroutine_handle<> h);
  void task_finished(uint64_t tid);
  // wrap (current task, handle) into a liveness-guarded resume closure
  std::function<void()> guarded_resume_here(std::coroutine_handle<> h);
  uint64_t draw_delivery();  // latency draw, or 0 if lost
  bool link_up(Addr src, Addr dst) {
    return is_connected(src) && is_connected(dst) &&
           blocked_pairs_.find({src, dst}) == blocked_pairs_.end();
  }
  struct Pending {
    bool settled = false;
    std::function<void(std::any)> finish;  // guarded; empty any = timeout
  };
  void send_reply(Addr from, Addr to, uint64_t rpc_id, std::any reply);
  std::unordered_map<uint64_t, std::shared_ptr<Pending>> pending_;
  uint64_t next_rpc_id_ = 1;
  uint64_t msg_count_ = 0;
  using RawHandler =
      std::function<void(Addr caller, uint64_t rpc_id, std::any payload)>;
  std::map<Addr, std::map<std::type_index, RawHandler>> handlers_;

 private:
  struct Event {
    uint64_t t;
    uint64_t seq;
    std::function<void()> fn;
  };
  struct EventCmp {
    bool operator()(const Event& a, const Event& b) const {
      return a.t != b.t ? a.t > b.t : a.seq > b.seq;
    }
  };
  uint64_t seed_;
  std::mt19937_64 rng_;
  uint64_t now_ = 0;
  uint64_t seq_ = 0;
  uint64_t trace_hash_ = 0xcbf29ce484222325ull;
  std::priority_queue<Event, std::vector<Event>, EventCmp> events_;
  NetConfig netcfg_;
  std::map<Addr, bool> node_connected_;
  std::set<std::pair<Addr, Addr>> blocked_pairs_;
  std::map<Addr, std::map<std::string, Bytes>> fs_;
  // task bookkeeping
  uint64_t next_task_ = 1;
  std::unordered_set<uint64_t> live_;
  std::unordered_map<uint64_t, std::coroutine_handle<>> frames_;
  std::unordered_map<uint64_t, Addr> task_addr_;
  std::map<Addr, std::set<uint64_t>> node_tasks_;  // live tasks per node
  std::vector<uint64_t> finished_;  // destroyed by run loop after each event
  Addr cur_addr_ = 0;
  uint64_t cur_task_ = 0;
};

// ----------------------------------------------------- template definitions

namespace detail {
template <class T>
template <class P>
void PromiseBase<T>::FinalAwaiter::await_suspend(
    std::coroutine_handle<P> h) noexcept {
  auto& p = h.promise();
  p.js->done = true;
  for (auto& w : p.js->waiters) p.sim->schedule(p.sim->now(), std::move(w));
  p.js->waiters.clear();
  p.sim->task_finished(p.task_id);  // frame destroyed by the run loop
}
}  // namespace detail

template <class T>
TaskRef<T> Sim::spawn(Addr node, Task<T> t) {
  auto h = t.release();
  auto& p = h.promise();
  p.sim = this;
  uint64_t tid = next_task_++;
  p.task_id = tid;
  live_.insert(tid);
  frames_[tid] = h;
  task_addr_[tid] = node;
  node_tasks_[node].insert(tid);
  schedule(now_, [this, tid, h] {
    if (!task_live(tid)) return;
    resume_in_context(tid, h);
  });
  return TaskRef<T>(p.js, tid, this);
}

template <class T>
void TaskRef<T>::abort() {
  if (sim_ && js_ && !js_->done) {
    js_->aborted = true;
    sim_->abort_task(id_);
  }
}

template <class T>
void TaskRef<T>::add_callback(std::function<void()> f) {
  if (js_->done)
    sim_->schedule(sim_->now(), std::move(f));
  else
    js_->waiters.push_back(std::move(f));
}

template <class T>
void TaskRef<T>::await_suspend(std::coroutine_handle<> h) {
  js_->waiters.push_back(sim_->guarded_resume_here(h));
}

template <class T>
void Channel<T>::send(T v) {
  st_->q.push_back(std::move(v));
  auto* sim = Sim::current();
  for (auto& w : st_->waiters) sim->schedule(sim->now(), std::move(w));
  st_->waiters.clear();
}
template <class T>
void Channel<T>::close() {
  st_->closed = true;
  auto* sim = Sim::current();
  for (auto& w : st_->waiters) sim->schedule(sim->now(), std::move(w));
  st_->waiters.clear();
}
template <class T>
void Channel<T>::RecvAwaiter::await_suspend(std::coroutine_handle<> h) {
  st->waiters.push_back(sim->guarded_resume_here(h));
}
template <class T>
typename Channel<T>::RecvAwaiter Channel<T>::recv() {
  return RecvAwaiter{Sim::current(), st_};
}

template <class Req>
void Sim::add_rpc_handler(std::function<Task<typename Req::Reply>(Req)> h) {
  using Rsp = typename Req::Reply;
  Addr node = cur_addr_;
  handlers_[node][std::type_index(typeid(Req))] =
      [this, node, h](Addr caller, uint64_t rpc_id, std::any payload) {
        Req req = std::any_cast<Req>(std::move(payload));
        TaskRef<Rsp> tr = spawn(node, h(std::move(req)));
        tr.add_callback([this, tr, node, caller, rpc_id]() {
          send_reply(node, caller, rpc_id, std::any(*tr.value()));
        });
      };
}

template <class Req>
auto Sim::call_timeout(Addr dst, Req req, uint64_t timeout_ns) {
  using Rsp = typename Req::Reply;
  // All registration happens eagerly here (still inside the calling task's
  // context, before suspension); the returned awaiter only parks the
  // continuation. State lives on the heap behind a shared_ptr owned by the
  // registered closures, so the awaiter carries no payload — gcc's coroutine
  // codegen bitwise-relocates aggregate awaiter temporaries, which corrupts
  // heap-owning members (observed with std::string payloads under ASan).
  struct CallState {
    std::optional<Rsp> result;
    bool done = false;
    std::coroutine_handle<> h{};
  };
  auto st = std::make_shared<CallState>();
  Sim* s = this;
  Addr src = cur_addr_;
  uint64_t tid = cur_task_;
  uint64_t rpc_id = next_rpc_id_++;
  auto pend = std::make_shared<Pending>();
  pend->finish = [s, st, tid](std::any reply) {
    if (reply.has_value()) st->result = std::any_cast<Rsp>(std::move(reply));
    st->done = true;
    // the resume closure re-captures `st` (keeps it alive through
    // await_resume) and carries the kill-guard: a dead task never resumes
    s->schedule(s->now(), [s, st, tid] {
      if (s->task_live(tid) && st->h) s->resume_in_context(tid, st->h);
    });
  };
  pending_[rpc_id] = pend;
  schedule(now_ + timeout_ns, [s, rpc_id] {
    auto it = s->pending_.find(rpc_id);
    if (it == s->pending_.end()) return;
    auto p = it->second;
    s->pending_.erase(it);
    if (!p->settled) {
      p->settled = true;
      p->finish(std::any());
    }
  });
  // request leg: loss/latency drawn at send; link re-checked at delivery
  uint64_t dt = link_up(src, dst) ? draw_delivery() : 0;
  if (dt != 0) {
    schedule(now_ + dt,
             [s, src, dst, rpc_id, r = std::move(req)]() mutable {
               if (!s->link_up(src, dst)) return;
               auto nit = s->handlers_.find(dst);
               if (nit == s->handlers_.end()) return;
               auto hit = nit->second.find(std::type_index(typeid(Req)));
               if (hit == nit->second.end()) return;  // node down / not serving
               s->msg_count_++;
               hit->second(src, rpc_id, std::any(std::move(r)));
             });
  }  // else: lost; the timeout will fire
  struct CallAwaiter {
    std::shared_ptr<CallState> st;
    bool await_ready() const { return st->done; }
    void await_suspend(std::coroutine_handle<> h) { st->h = h; }
    std::optional<Rsp> await_resume() { return std::move(st->result); }
  };
  return CallAwaiter{std::move(st)};
}

template <class T>
auto Task<T>::operator co_await() && {
  return Sim::current()->spawn(std::move(*this));  // TaskRef is awaitable
}
inline auto Task<void>::operator co_await() && {
  return Sim::current()->spawn(std::move(*this));
}

}  // namespace simcore
