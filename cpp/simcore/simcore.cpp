#include "simcore.h"
#include <chrono>
#include <cstdarg>

namespace simcore {

static thread_local Sim* g_current = nullptr;

Sim::Sim(uint64_t seed) : seed_(seed), rng_(seed) { g_current = this; }
Sim::~Sim() {
  // destroy any still-live frames (tests that end with tasks running)
  for (auto& [tid, h] : frames_) {
    if (live_.count(tid)) h.destroy();
  }
  if (g_current == this) g_current = nullptr;
}

Sim* Sim::current() { return g_current; }

void Sim::schedule(uint64_t at, std::function<void()> fn) {
  events_.push(Event{at < now_ ? now_ : at, seq_++, std::move(fn)});
}

void Sim::resume_in_context(uint64_t tid, std::coroutine_handle<> h) {
  Addr prev_addr = cur_addr_;
  uint64_t prev_task = cur_task_;
  cur_addr_ = task_addr_[tid];
  cur_task_ = tid;
  h.resume();
  cur_addr_ = prev_addr;
  cur_task_ = prev_task;
}

void Sim::task_finished(uint64_t tid) {
  live_.erase(tid);
  auto it = task_addr_.find(tid);
  if (it != task_addr_.end()) node_tasks_[it->second].erase(tid);
  finished_.push_back(tid);
}

std::function<void()> Sim::guarded_resume_here(std::coroutine_handle<> h) {
  uint64_t tid = cur_task_;
  return [this, tid, h] {
    if (task_live(tid)) resume_in_context(tid, h);
  };
}

void Sim::abort_task(uint64_t tid) {
  if (!live_.count(tid)) return;
  live_.erase(tid);
  auto it = frames_.find(tid);
  if (it != frames_.end()) {
    it->second.destroy();
    frames_.erase(it);
  }
  auto at = task_addr_.find(tid);
  if (at != task_addr_.end()) node_tasks_[at->second].erase(tid);
  task_addr_.erase(tid);
}

void Sim::kill(Addr node) {
  // crash semantics (reference Handle::kill, tester.rs:329-333): all the
  // node's tasks die, its RPC handlers vanish (in-flight requests to it get
  // dropped -> caller timeout), its files survive for restart/restore.
  auto it = node_tasks_.find(node);
  if (it != node_tasks_.end()) {
    for (uint64_t tid : it->second) {
      if (!live_.count(tid)) continue;
      live_.erase(tid);
      auto fit = frames_.find(tid);
      if (fit != frames_.end()) {
        fit->second.destroy();
        frames_.erase(fit);
      }
      task_addr_.erase(tid);
    }
    it->second.clear();
  }
  handlers_.erase(node);
}

uint64_t Sim::draw_delivery() {
  // per-message decisions, like the reference's loss/latency model
  // (tester.rs:127-137); draw order fixed for determinism
  double loss = netcfg_.packet_loss_rate;
  uint64_t lat = netcfg_.send_latency_min == netcfg_.send_latency_max
                     ? netcfg_.send_latency_min
                     : rand_range(netcfg_.send_latency_min,
                                  netcfg_.send_latency_max + 1);
  if (loss > 0.0 && rand_bool(loss)) return 0;
  return lat == 0 ? 1 : lat;
}

void Sim::send_reply(Addr from, Addr to, uint64_t rpc_id, std::any reply) {
  if (!link_up(from, to)) return;
  uint64_t dt = draw_delivery();
  if (dt == 0) return;  // reply lost; caller times out
  schedule(now_ + dt, [this, from, to, rpc_id, reply = std::move(reply)]() mutable {
    if (!link_up(from, to)) return;
    auto it = pending_.find(rpc_id);
    if (it == pending_.end()) return;  // caller gave up (timeout fired)
    auto p = it->second;
    pending_.erase(it);
    msg_count_++;
    if (!p->settled) {
      p->settled = true;
      p->finish(std::move(reply));
    }
  });
}

void Sim::SleepAwaiter::await_suspend(std::coroutine_handle<> h) {
  sim->schedule(sim->now() + dur, sim->guarded_resume_here(h));
}

bool Sim::run(Task<void> main) {
  g_current = this;
  const auto& wd = watchdog();
  const auto wd_real0 = std::chrono::steady_clock::now();
  const uint64_t wd_virt0 = now_;
  uint64_t wd_countdown = 0;
  auto ref = spawn(Addr(0), std::move(main));
  while (!ref.done()) {
    if (events_.empty()) return false;  // deadlock
    if (wd.enabled && wd_countdown-- == 0) {
      wd_countdown = 8192;  // amortize the clock read
      double real = std::chrono::duration<double>(
                        std::chrono::steady_clock::now() - wd_real0)
                        .count();
      double virt = (now_ - wd_virt0) / 1e9;
      const char* name = wd.name_fn ? wd.name_fn() : "?";
      if (wd.real_cap_s > 0 && real > wd.real_cap_s) {
        std::fprintf(stderr,
                     "[WDOG ] test %s exceeded %.0fs real time — liveness "
                     "failure (real %.2fs, virtual %.2fs)\n",
                     name, wd.real_cap_s, real, virt);
        std::abort();
      }
      if (wd.virt_cap_s > 0 && virt > wd.virt_cap_s) {
        std::fprintf(stderr,
                     "[WDOG ] test %s exceeded %.0fs VIRTUAL time — livelock "
                     "burning virtual time (real %.2fs, virtual %.2fs)\n",
                     name, wd.virt_cap_s, real, virt);
        std::abort();
      }
    }
    Event ev = std::move(const_cast<Event&>(events_.top()));
    events_.pop();
    now_ = ev.t;
    // fold the pop into the determinism trace (FNV-1a style); both timestamp
    // and sequence number, so even same-timestamp reorderings are caught
    trace_hash_ ^= ev.t + 0x9e3779b97f4a7c15ull + (trace_hash_ << 6);
    trace_hash_ *= 0x100000001b3ull;
    trace_hash_ ^= ev.seq + 0x9e3779b97f4a7c15ull + (trace_hash_ << 6);
    trace_hash_ *= 0x100000001b3ull;
    ev.fn();
    for (uint64_t tid : finished_) {
      auto it = frames_.find(tid);
      if (it != frames_.end()) {
        it->second.destroy();
        frames_.erase(it);
      }
      task_addr_.erase(tid);
    }
    finished_.clear();
  }
  if (trace_observer()) trace_observer()(trace_hash_);
  return true;
}

namespace log_detail {
void log_line(const char* module, const char* fmt, ...) {
  Sim* sim = Sim::current();
  if (sim)
    std::fprintf(stderr, "[%9.4fs %-8s %s] ", sim->now() / 1e9, module,
                 addr_str(sim->cur_addr()).c_str());
  else
    std::fprintf(stderr, "[          %-8s      ] ", module);
  va_list args;
  va_start(args, fmt);
  std::vfprintf(stderr, fmt, args);
  va_end(args);
  std::fputc('\n', stderr);
}
}  // namespace log_detail

}  // namespace simcore
